"""Autoregressive decode serving: continuous batching over a paged KV
cache (ISSUE 6; PAPERS.md: Ragged Paged Attention).

The one-shot engine (engine.py) answers each request with one model
run. Autoregressive decode is different in kind: a request is a
SEQUENCE of dependent steps (one per generated token), each step needs
the sequence's whole KV history on-device, and sequences finish at
ragged, data-dependent times. Two naive designs fail on TPU:

  - drain-per-batch (admit a batch, run every member to completion,
    then admit the next): short sequences finish early and their slots
    idle until the longest member drains — realized tokens/s decays
    with length variance (decode_bench measures exactly this);
  - per-sequence shapes: recompiling per ragged length mints O(shapes)
    jit entries under the traffic that can least afford compiles.

This engine does CONTINUOUS batching over FIXED compiled shapes:

  - the decode batch has a fixed slot layout — slot count padded to a
    small ladder (``FLAGS['decode_slots']``), per-slot page-table width
    padded to a derived ladder — and ``warm()`` pre-compiles every
    (slots, width) pair at load time, exactly like the one-shot
    engine's bucket warm. After warmup a churn of admits/completions
    at ragged lengths performs ZERO new compiles (tier-1 pins the
    ``serving.decode.compiles`` counter);
  - every step consumes up to ``prefill_chunk`` PROMPT tokens plus one
    generated token per decoding slot (ISSUE 10, chunked prefill):
    sequences still in their prompt are granted chunks of it — causal
    within the chunk, all slots sharing a per-step token BUDGET of
    ``prefill_chunk`` prompt tokens — while sequences past their
    prompt consume their previously sampled token, all in the SAME
    compiled mixed batch (Sarathi-style). A P-token prompt completes
    prefill in ``ceil(P / prefill_chunk)`` steps instead of P, so
    time-to-first-token stops being linear in prompt length, and
    in-flight decodes never stall behind a long prompt. New sequences
    are admitted into free slots BETWEEN steps, mid-flight of everyone
    else — admission never waits for a batch boundary;
  - K/V live in the preallocated paged pool (kv_cache.py): HBM is
    bounded at construction, pages are reserved at admission (refusal
    is an immediate structured ``ServerOverloaded``) and recycled at
    completion, and the paged-attention kernel reads through the page
    tables so ragged histories share one compiled shape.

SPECULATIVE DECODING (ISSUE 14): with a small DRAFT decoder attached
(``draft_spec``/``draft_params`` + ``spec_k > 0``), every decoding slot
advances up to ``spec_k + 1`` tokens per scheduler round for ONE
target-model step: the draft proposes ``spec_k`` tokens (cheap batched
steps on its own compiled ladder), the target verifies all ``k+1``
positions in one ``decoder_step_chunked(all_lanes=True)`` call, and the
committed tokens are the target's own deterministic per-(seed,
position) choices along the longest agreeing prefix — so output is
BITWISE what the non-speculative engine emits, for greedy and seeded
sampling alike (the classic draft/verify trade from *Fast Inference
from Transformers via Speculative Decoding*, with the realization
pinned by the seeded sampler instead of stochastic rejection). The
draft's KV pool MIRRORS the target's page geometry — same allocator,
same page ids, same tables — so reservation growth, rejected-suffix
rollback (``PageAllocator.shrink``), COW, preemption spill and restore
stay one mechanism; a rejected suffix un-notes its tokens and frees
any page that held only rejected positions. ``spec_k`` is a PR 8
tunable (``effective_flag('spec_k')``, 0 = off and bit-identical old
behavior).

The model behind the step is pluggable via the ``DecoderSpec`` /
``build_decoder_params`` / ``decoder_step`` contract below; the
built-in spec'd decoder (embedding + N pre-norm transformer layers
with paged attention + tied-embedding logits, deterministic params
from a seed) is the test/bench/selftest vehicle — real checkpoints
implement the same step signature.

Lifecycle mirrors the one-shot engine so the SAME ModelRegistry
hot-swaps decoders: ``stop(drain=True)`` finishes every admitted
sequence then drops params/pools/compiled steps (executables release
on retirement); a failed ``warm()`` stops the scheduler before
re-raising so the registry's rollback leaks nothing.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..autotune.ladder import observe as _observe_shape
from ..distributed import faults as _faults
from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger
from .engine import bucket_for as _bucket_for, resolve_bucket_spec
from .errors import (DeadlineExceeded, EngineRetired, RequestTooLarge,
                     ServerOverloaded, ServingError)
from .kv_cache import GARBAGE_PAGE, HostSpillStore, PagedKvCache

__all__ = ["DecoderSpec", "DecodeEngine", "build_decoder_params",
           "decoder_step", "decoder_step_chunked", "width_ladder",
           "sample_token", "validate_draft_spec"]

_log = get_logger("serving")

_m_requests = _metrics.counter("serving.decode.requests")
_m_admitted = _metrics.counter("serving.decode.admitted")
_m_completions = _metrics.counter("serving.decode.completions")
_m_steps = _metrics.counter("serving.decode.steps")
_m_tokens = _metrics.counter("serving.decode.tokens")
_m_overloads = _metrics.counter("serving.decode.overloads")
_m_deadline_miss = _metrics.counter("serving.decode.deadline_misses")
_m_cancels = _metrics.counter("serving.decode.cancels")
# one inc per DISTINCT (slots, width) shape the step compiles — after
# warm() this must never move again (the tier-1 churn guard pins it)
_m_compiles = _metrics.counter("serving.decode.compiles")
_m_step_ms = _metrics.histogram("serving.decode.step_ms")
_m_queue_wait = _metrics.histogram("serving.decode.queue_wait_ms")
_m_total = _metrics.histogram("serving.decode.total_ms")
# live slots / slot bucket per step: the continuous-batching win is
# this histogram staying fat while drain-per-batch's decays
_m_occupancy = _metrics.histogram("serving.decode.occupancy")
# chunked prefill (ISSUE 10): prompt tokens consumed via prefill
# grants, per-step grant totals (prices the token-budget policy next
# to the occupancy/fragmentation gauges), and how many scheduler steps
# each request waited for its FIRST generated token — the
# load-independent evidence chunking exists for (ceil(P/chunk) + queue
# wait, vs P + queue wait unchunked)
_m_prefill_tokens = _metrics.counter("serving.decode.prefill_tokens")
_m_prefill_per_step = _metrics.histogram(
    "serving.decode.prefill_tokens_per_step")
_m_first_token_steps = _metrics.histogram(
    "serving.decode.steps_to_first_token")
# preempt+restore (ISSUE 13, demand-mode reservation): preemptions
# spill a victim's pages to host and requeue it at the front; restores
# scatter them back bitwise; demotions release a QUEUED reservation
# (no computed work lost) so a live grower can proceed
_m_preemptions = _metrics.counter("serving.kv.preemptions")
_m_restores = _metrics.counter("serving.kv.restores")
_m_demotions = _metrics.counter("serving.kv.demotions")
# speculative decoding (ISSUE 14): TARGET-model invocations — one per
# plain/prefill step AND one per verify chunk (warm included; benches
# delta it). The headline ratio is target_steps per generated token:
# spec off it is 1 per token, spec on a verify commits up to k+1
_m_target_steps = _metrics.counter("serving.decode.target_steps")
# DRAFT-model invocations (propose + prefill shadowing) — the cheap
# steps speculation trades for target steps
_m_draft_steps = _metrics.counter("serving.decode.spec.draft_steps")
# proposed == accepted + rejected, always (counter-pinned in tier-1);
# accept_rate histogram observes each finished request's ratio
_m_spec_proposed = _metrics.counter("serving.decode.spec.proposed")
_m_spec_accepted = _metrics.counter("serving.decode.spec.accepted")
_m_spec_rejected = _metrics.counter("serving.decode.spec.rejected")
_m_spec_accept_rate = _metrics.histogram(
    "serving.decode.spec.accept_rate")
# workload layer (ISSUE 20): constrained decode applies a token-mask
# automaton to the logits row before the per-(seed, position) choice
# (masked_tokens counts them); prompt-only embedding/scoring requests
# ride the chunked-prefill path in their OWN slot lane — decode
# live_slots never moves for them (counter-pinned in tier-1)
_m_masked_tokens = _metrics.counter("serving.decode.masked_tokens")
_m_embed_requests = _metrics.counter("serving.decode.embed.requests")
_m_embed_steps = _metrics.counter("serving.decode.embed.steps")
_m_embed_tokens = _metrics.counter("serving.decode.embed.tokens")


# --- the pluggable decoder model ----------------------------------------

class DecoderSpec:
    """Architecture + identity of a decoder the engine can serve.
    ``d_model == n_heads * head_dim`` (enforced); ``n_heads`` must be a
    multiple of ``n_kv_heads`` (GQA). Params are DETERMINISTIC in
    ``seed`` so two replicas loading the same spec serve bitwise the
    same model — and tests can reference-check outputs."""

    __slots__ = ("vocab", "d_model", "n_layers", "n_heads", "n_kv_heads",
                 "head_dim", "seed", "eos_id")

    def __init__(self, vocab: int = 64, d_model: int = 32,
                 n_layers: int = 2, n_heads: int = 4,
                 n_kv_heads: Optional[int] = None, seed: int = 0,
                 eos_id: Optional[int] = None):
        self.vocab = int(vocab)
        self.d_model = int(d_model)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.n_kv_heads = int(n_kv_heads if n_kv_heads is not None
                              else n_heads)
        if self.d_model % 2:
            raise ValueError(f"d_model {d_model} must be even "
                             f"(sinusoidal encoding pairs sin/cos halves)")
        if self.d_model % self.n_heads:
            raise ValueError(f"d_model {d_model} not divisible by "
                             f"n_heads {n_heads}")
        if self.n_heads % self.n_kv_heads:
            raise ValueError(f"n_heads {n_heads} not a multiple of "
                             f"n_kv_heads {self.n_kv_heads}")
        self.head_dim = self.d_model // self.n_heads
        self.seed = int(seed)
        self.eos_id = None if eos_id is None else int(eos_id)

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in
                ("vocab", "d_model", "n_layers", "n_heads", "n_kv_heads",
                 "seed", "eos_id")}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DecoderSpec":
        allowed = ("vocab", "d_model", "n_layers", "n_heads",
                   "n_kv_heads", "seed", "eos_id")
        # reject, don't drop: a misspelled field silently deploying a
        # default-architecture decoder is a wrong-model hot-swap
        # (head_dim is derived — accepted only if consistent)
        unknown = sorted(set(d) - set(allowed) - {"head_dim"})
        if unknown:
            raise ValueError(
                f"unknown DecoderSpec field(s) {unknown}; "
                f"valid: {sorted(allowed)}")
        spec = cls(**{k: v for k, v in d.items() if k in allowed})
        if "head_dim" in d and int(d["head_dim"]) != spec.head_dim:
            raise ValueError(
                f"head_dim {d['head_dim']} contradicts d_model "
                f"{spec.d_model} / n_heads {spec.n_heads} = "
                f"{spec.head_dim} — head_dim is derived, not free")
        return spec


def validate_draft_spec(target: DecoderSpec, draft: DecoderSpec):
    """Cross-validate a speculative DRAFT decoder against its target
    (ISSUE 14 satellite): a mismatched draft must fail at LOAD, typed
    and naming the field, not mid-verify with garbage acceptance. The
    draft proposes token ids the target scores, so the vocabularies
    must be identical; page geometry (page_size / num_pages) is shared
    BY CONSTRUCTION — the draft's pool mirrors the target's allocator
    and page tables, so it cannot diverge. Everything architectural
    (layers, heads, d_model) is free: that asymmetry is the whole
    speedup."""
    if draft.vocab != target.vocab:
        raise ValueError(
            f"draft/target DecoderSpec mismatch on field 'vocab': "
            f"draft {draft.vocab} != target {target.vocab} — the draft "
            f"proposes token ids the target must score")
    if draft.eos_id != target.eos_id:
        raise ValueError(
            f"draft/target DecoderSpec mismatch on field 'eos_id': "
            f"draft {draft.eos_id} != target {target.eos_id} — "
            f"termination is decided on committed (target-verified) "
            f"tokens, so the specs must agree on it")


def build_decoder_params(spec: DecoderSpec) -> Dict[str, Any]:
    """Deterministic parameter tree (seeded numpy draws, scaled-normal
    init) — the test/bench stand-in for loading a checkpoint."""
    import jax.numpy as jnp

    rng = np.random.RandomState(spec.seed)
    dm, dh = spec.d_model, spec.head_dim

    def mat(fan_in, *shape):
        return jnp.asarray(
            (rng.randn(*shape) / math.sqrt(fan_in)).astype(np.float32))

    params: Dict[str, Any] = {
        "tok_emb": mat(dm, spec.vocab, dm),
        "lnf": (jnp.ones((dm,), jnp.float32), jnp.zeros((dm,), jnp.float32)),
    }
    for l in range(spec.n_layers):
        params[f"layer{l}"] = {
            "ln1": (jnp.ones((dm,), jnp.float32),
                    jnp.zeros((dm,), jnp.float32)),
            "wq": mat(dm, dm, spec.n_heads * dh),
            "wk": mat(dm, dm, spec.n_kv_heads * dh),
            "wv": mat(dm, dm, spec.n_kv_heads * dh),
            "wo": mat(dm, spec.n_heads * dh, dm),
            "ln2": (jnp.ones((dm,), jnp.float32),
                    jnp.zeros((dm,), jnp.float32)),
            "w1": mat(dm, dm, 4 * dm),
            "w2": mat(4 * dm, 4 * dm, dm),
        }
    return params


def _ln(x, gb):
    import jax.numpy as jnp

    g, b = gb
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-6) * g + b


def _pos_encoding(positions, d_model):
    """Sinusoidal [B, d_model] — unbounded positions, no learned table
    to cap sequence length."""
    import jax.numpy as jnp

    half = d_model // 2
    freq = jnp.exp(-math.log(10000.0) * jnp.arange(half) / half)
    ang = positions[:, None].astype(jnp.float32) * freq[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)


def decoder_step_chunked(params, spec: DecoderSpec, tokens, positions,
                         q_lens, k_pool, v_pool, page_tables, kv_lens,
                         all_lanes: bool = False,
                         return_hidden: bool = False):
    """ONE mixed decode/prefill step for a fixed-slot batch
    (ISSUE 10). Each slot carries up to C tokens of ITS sequence — a
    prefill chunk, a single decode token at C lane 0, or nothing —
    attending causally within the chunk. Functional: writes every
    valid lane's K/V into the paged pools (dead lanes and dead slots
    write the garbage page), attends through the page tables, returns
    ``(k_pool, v_pool, logits [B, vocab])``.

    tokens/positions: [B, C] int32, lane ``j`` of slot ``i`` valid iff
    ``j < q_lens[i]`` (invalid lanes: 0/0 — masked to the garbage
    page, never trusted). kv_lens: [B] int32 — valid keys INCLUDING
    this step's q_len tokens. Chunking is pure packing: the math per
    token is identical to feeding the same tokens one step at a time
    (the chunked-vs-unchunked greedy-equality test pins it).

    Logits come back ONLY for each slot's newest lane (``q_len - 1``)
    — the one position the scheduler ever samples from (a chunk that
    doesn't finish its prompt uses no logits at all). Unembedding is
    the widest matmul of the step: unembedding all C lanes would waste
    ~(C-1)/C of it plus a C-times-larger device->host transfer on
    every prefill step.

    ``all_lanes=True`` is the SPECULATIVE-VERIFY form (ISSUE 14):
    logits come back for EVERY lane (``[B, C, vocab]``) — lane ``j`` is
    the target's distribution for position ``positions[:, j] + 1``, so
    one call scores a draft's ``k`` proposals plus the bonus position.
    The full-lane unembed is exactly the price of verification (C =
    spec_k + 1 lanes, not the prefill chunk width); acceptance happens
    host-side in the engine.

    ``return_hidden=True`` (requires ``all_lanes``) additionally
    returns the final-norm hidden states ``[B, C, d_model]`` — the
    EMBEDDING/SCORING form (ISSUE 20): one chunked call yields both
    every lane's pooled-representation input and its next-token
    distribution (per-token logprobs), so prompt-only scoring requests
    ride the exact prefill path generation uses.
    """
    import jax
    import jax.numpy as jnp

    from ..fluid.ops.pallas_kernels.paged_attention import paged_attention

    b, c = tokens.shape
    ps = k_pool.shape[2]
    dm, dh = spec.d_model, spec.head_dim
    lane = jnp.arange(c)[None, :]                      # [1, C]
    valid = lane < q_lens[:, None]                     # [B, C]
    x = params["tok_emb"][tokens] * math.sqrt(dm) + \
        _pos_encoding(positions.reshape(-1), dm).reshape(b, c, dm)
    page_idx = positions // ps
    # each lane's physical page: its slot's table row at the token's
    # page index. Invalid lanes (j >= q_len, padded dead slots) are
    # FORCED to the garbage page — a live slot's row 0 must never be
    # clobbered by a dead lane's position-0 write
    page = jnp.where(valid,
                     jnp.take_along_axis(page_tables, page_idx, axis=1),
                     GARBAGE_PAGE)                     # [B, C]
    off = jnp.where(valid, positions % ps, 0)
    for l in range(spec.n_layers):
        lp = params[f"layer{l}"]
        h = _ln(x, lp["ln1"])
        q = (h @ lp["wq"]).reshape(b, c, spec.n_heads, dh)
        k = (h @ lp["wk"]).reshape(b, c, spec.n_kv_heads, dh)
        v = (h @ lp["wv"]).reshape(b, c, spec.n_kv_heads, dh)
        # write the whole chunk's K/V, THEN attend: within the chunk,
        # query j sees keys i <= j of the same chunk — write-before-
        # attend makes the chunk exactly equal to sequential steps
        k_pool = k_pool.at[l, page, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[l, page, off].set(v.astype(v_pool.dtype))
        attn = paged_attention(q, k_pool[l], v_pool[l], page_tables,
                               kv_lens, q_lens=q_lens)
        x = x + attn.reshape(b, c, spec.n_heads * dh) @ lp["wo"]
        h2 = _ln(x, lp["ln2"])
        x = x + jax.nn.gelu(h2 @ lp["w1"]) @ lp["w2"]
    if all_lanes:
        # verify form: every lane's logits ([B, C, vocab]) — the
        # acceptance walk needs the target's distribution at each
        # proposed position, not just the newest
        h = _ln(x, params["lnf"])
        logits = h @ params["tok_emb"].T
        if return_hidden:
            return k_pool, v_pool, logits, h
        return k_pool, v_pool, logits
    # unembed only each slot's newest lane (dead slots gather lane 0 —
    # garbage the scheduler never samples)
    last = jnp.maximum(q_lens - 1, 0)[:, None, None]       # [B, 1, 1]
    x_last = jnp.take_along_axis(
        x, jnp.broadcast_to(last, (b, 1, dm)), axis=1)[:, 0]
    logits = _ln(x_last, params["lnf"]) @ params["tok_emb"].T
    return k_pool, v_pool, logits


def decoder_step(params, spec: DecoderSpec, tokens, positions,
                 k_pool, v_pool, page_tables, kv_lens):
    """The PR 6 single-token step — now the C=1 case of
    ``decoder_step_chunked`` (one implementation, so the two forms
    cannot drift). tokens/positions: [B] int32 (dead slots: 0/0 with
    an all-garbage table row); kv_lens: [B] int32 — valid keys
    INCLUDING this step's token (0 = dead slot -> exact-zero attention
    output). Returns ``(k_pool, v_pool, logits [B, vocab])``."""
    import jax.numpy as jnp

    q_lens = (kv_lens > 0).astype(jnp.int32)
    return decoder_step_chunked(
        params, spec, tokens[:, None], positions[:, None], q_lens,
        k_pool, v_pool, page_tables, kv_lens)


# --- sampling -----------------------------------------------------------

def sample_token(logits_row, temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, position: int = 0) -> int:
    """Sampling policy for ONE generated token (the ROADMAP
    sampling-beyond-greedy residual): greedy argmax at temperature 0
    (the default — bitwise the PR 6 behavior), else temperature-scaled
    softmax over the ``top_k`` highest logits (0 = full vocab), drawn
    from an rng derived ONLY from ``(seed, position)``.

    Deterministic given the request's seed, and — because position is
    the token's absolute index in ITS sequence — independent of batch
    composition, slot assignment, and admission order: continuous
    batching cannot perturb a request's sampled output (tier-1 pins a
    request decoding identically through two differently-loaded
    engines)."""
    row = np.asarray(logits_row, np.float64)
    if temperature <= 0.0:
        return int(np.argmax(row))
    row = row / float(temperature)
    k = int(top_k)
    if 0 < k < row.size:
        kth = np.partition(row, -k)[-k]
        row = np.where(row < kth, -np.inf, row)
    row = row - row.max()
    p = np.exp(row)
    p /= p.sum()
    rng = np.random.Generator(np.random.PCG64(np.random.SeedSequence(
        [int(seed) & 0xFFFFFFFF, int(position)])))
    return int(rng.choice(row.size, p=p))


# --- ladders ------------------------------------------------------------

def width_ladder(max_pages: int) -> List[int]:
    """Page-table width buckets: powers of two up to (and always
    including) the worst case — the second padded dimension of the
    compiled decode shape."""
    if max_pages < 1:
        raise ValueError(f"max_pages must be >= 1, got {max_pages}")
    out, w = [], 1
    while w < max_pages:
        out.append(w)
        w *= 2
    out.append(max_pages)
    return sorted(set(out))


# --- requests / slots ---------------------------------------------------

class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "deadline", "ev", "result", "error",
                 "t_enq", "seq_id", "trace_ctx", "temperature", "top_k",
                 "seed", "produced", "cached_tokens", "cow", "resume_pos",
                 "published", "carry_steps", "carry_fts", "needs_alloc",
                 "resume_dpos", "spec_proposed", "spec_accepted",
                 "mask", "mask_state", "want_topk", "first_topk")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 deadline: Optional[float], seq_id: int,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 mask: Optional[Any] = None, want_topk: int = 0):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.deadline = deadline
        self.ev = threading.Event()
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[BaseException] = None
        self.t_enq = time.monotonic()
        self.seq_id = seq_id
        self.trace_ctx = _tracing.wire_context()
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        # generated tokens, appended by the answer phase UNDER the
        # engine's _cond. Living on the REQUEST (not the slot) so
        # streaming readers (stream_tokens, ISSUE 12) can see tokens
        # the moment they exist, long before the sequence finishes
        self.produced: List[int] = []
        # prefix caching + preemption state (ISSUE 13) — on the REQUEST
        # because preemption round-trips a sequence through the queue:
        # cached_tokens = prompt tokens answered from the prefix index
        # (prefill starts past them); cow = the pending private-copy of
        # a shared partial page (executed by the scheduler before the
        # first step, then None); resume_pos/carry_* = the exact point
        # a preempted sequence continues from; needs_alloc = the
        # reservation was surrendered (preempt/demote) and admission
        # must re-reserve before taking a slot
        self.cached_tokens = 0
        self.cow: Optional[Dict[str, int]] = None
        self.resume_pos: Optional[int] = None
        self.published = False
        self.carry_steps = 0
        self.carry_fts: Optional[int] = None
        self.needs_alloc = False
        # speculative decoding (ISSUE 14): the draft pool's valid-write
        # watermark carried through preemption (mirrors resume_pos),
        # and the request's propose/accept tallies (accept_rate in the
        # result dict)
        self.resume_dpos: Optional[int] = None
        self.spec_proposed = 0
        self.spec_accepted = 0
        # constrained decode (ISSUE 20): a compiled MaskAutomaton and
        # its current state. On the REQUEST (not the slot) because the
        # state must survive preemption round-trips — produced tokens
        # never roll back on the plain path, so the automaton resumes
        # exactly where it stopped. want_topk asks the answer phase to
        # capture the FIRST generated position's top-k token order
        # (first_topk) — the n-best/beam fork point.
        self.mask = mask
        self.mask_state = mask.start if mask is not None else 0
        self.want_topk = int(want_topk)
        self.first_topk: Optional[List[int]] = None

    def fail(self, err: BaseException):
        self.error = err
        self.ev.set()


class _Slot:
    __slots__ = ("req", "pos", "pages_held", "steps", "first_token_steps",
                 "pending_restore", "dpos")

    def __init__(self, req: _DecodeRequest, pages_held: int):
        self.req = req
        self.pos = 0                # tokens already written to the cache
        self.pages_held = pages_held
        self.steps = 0              # scheduler steps this slot has ridden
        self.first_token_steps: Optional[int] = None
        # a preempted sequence's spilled pages must scatter back into
        # its fresh reservation BEFORE its next step (restore-before-
        # step): set at re-admission, executed by _prepare
        self.pending_restore = False
        # speculative decoding (ISSUE 14): positions validly written to
        # the DRAFT pool. Invariant: pos - 1 <= dpos <= pos — the draft
        # lags by at most one committed token (exactly one after a
        # fully-accepted round, whose last proposal it never fed
        # itself), so the next propose round catches up with a <= 2-
        # lane chunk before proposing
        self.dpos = 0

    def token_at(self, idx: int) -> int:
        """The sequence's token at absolute position ``idx``: a prompt
        token, or a previously generated one."""
        p = self.req.prompt
        return (int(p[idx]) if idx < len(p)
                else self.req.produced[idx - len(p)])


class _EmbedRequest:
    """A prompt-only embedding/scoring request (ISSUE 20): admitted by
    the same reserve-at-admission math with ``max_new = 0`` (the
    reservation is exactly the prompt's pages — there is no decode
    tail to headroom for), prefilled by the same chunked step, and
    NEVER occupying a decode slot: the embed lane has its own slot
    list and gauge, so ``serving.decode.live_slots`` is pinned
    unchanged while embeddings flow. Carries ``cow``/``seq_id``/
    ``fail`` so ``_fail_locked`` treats both request classes
    uniformly."""

    __slots__ = ("prompt", "deadline", "ev", "result", "error", "t_enq",
                 "seq_id", "trace_ctx", "cow", "hidden_sum", "logprobs")

    def __init__(self, prompt: np.ndarray, deadline: Optional[float],
                 seq_id: int, d_model: int):
        self.prompt = prompt
        self.deadline = deadline
        self.ev = threading.Event()
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[BaseException] = None
        self.t_enq = time.monotonic()
        self.seq_id = seq_id
        self.trace_ctx = _tracing.wire_context()
        self.cow: Optional[Dict[str, int]] = None
        # float64 running sum of final-norm hidden states — mean-pooled
        # over the prompt at completion — and the per-token logprobs
        # (position p scores prompt[p+1]; P-1 values for a P-token
        # prompt), both appended by the embed answer phase under _cond
        self.hidden_sum = np.zeros(d_model, np.float64)
        self.logprobs: List[float] = []

    def fail(self, err: BaseException):
        self.error = err
        self.ev.set()


class _EmbedSlot:
    __slots__ = ("req", "pos", "pages_held", "steps")

    def __init__(self, req: _EmbedRequest, pages_held: int):
        self.req = req
        self.pos = 0                # prompt tokens already prefilled
        self.pages_held = pages_held
        self.steps = 0


# --- the engine ---------------------------------------------------------

class DecodeEngine:
    """Continuous-batching autoregressive decode over one loaded
    decoder. Registry/server-compatible: ``name``/``version``/``kind``/
    ``stats()``/``stop(drain=)`` mirror InferenceEngine, so the same
    ModelRegistry hot-swaps decoders with the same drain guarantee."""

    kind = "decoder"

    def __init__(self, spec: DecoderSpec, *, name: str = "decoder",
                 version: int = 1,
                 slots: Optional[Sequence[int]] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 continuous: bool = True,
                 params: Optional[Dict[str, Any]] = None,
                 prefix_cache: Optional[bool] = None,
                 reservation: Optional[str] = None,
                 spill_dir: Optional[str] = None,
                 draft_spec: Optional[Any] = None,
                 draft_params: Optional[Dict[str, Any]] = None,
                 spec_k: Optional[int] = None,
                 mesh: Optional[Any] = None,
                 mesh_rules: Optional[Any] = None,
                 embeddings: bool = False,
                 warm: bool = True):
        from ..fluid.flags import FLAGS, effective_flag

        self.name = str(name)
        self.version = int(version)
        self.spec = spec
        # mesh-sharded serving (ISSUE 15): one replica SPANS chips.
        # `mesh` is a MeshSpec / axes dict / "tp=2" string (None reads
        # FLAGS['serving_mesh_axes']; '' = single-chip, bit-identical
        # PR 6 behavior). Params shard per name-matched `mesh_rules`
        # (default mesh.decoder_rules) and the paged KV pool shards
        # over the kv-head axis — the axis the wk/wv rules put on their
        # column dim — with the step fns' out_shardings pinned so churn
        # still compiles nothing post-warm.
        mesh_arg = FLAGS["serving_mesh_axes"] if mesh is None else mesh
        self._mesh_spec = None
        self._mesh = None
        self._mesh_rules = None
        self._kv_head_axes = None
        if mesh_arg:
            from ..mesh import (MeshSpec, ShardingRules, decoder_rules,
                                note_mesh)

            self._mesh_spec = MeshSpec.coerce(mesh_arg)
            self._mesh = self._mesh_spec.build()
            rules = ShardingRules.coerce(mesh_rules,
                                         default=decoder_rules)
            self._mesh_rules = rules
            self._kv_head_axes = self._kv_pool_axes(rules)
            self._check_kv_divisible("target", spec)
            note_mesh(self._mesh, label=f"decode:{name}.v{version}")
        # shares _step_mu with the compiled step + shape set: the lock
        # serializes every read-step-rebind against retirement's drop
        self._params = (build_decoder_params(spec)
                        if params is None else params)  # guarded-by: _step_mu
        if self._mesh is not None:
            from ..mesh import shard_param_tree

            self._params = shard_param_tree(self._params, self._mesh,
                                            self._mesh_rules)
        # slots="auto" resolves through the tuner exactly like the
        # one-shot engine's buckets="auto": a derived ladder from the
        # observed slot-demand histogram (or the cached one), else the
        # static FLAGS default — fixed before warm() either way
        self._slot_ladder = resolve_bucket_spec(
            FLAGS["decode_slots"] if slots is None else slots,
            tunable_id="decode_slots", fallback="1,2,4")
        self._max_slots = self._slot_ladder[-1]
        ps = int(FLAGS["kv_page_size"] if page_size is None else page_size)
        npages = int(FLAGS["kv_num_pages"] if num_pages is None
                     else num_pages)
        self.max_seq_len = int(FLAGS["decode_max_seq_len"]
                               if max_seq_len is None else max_seq_len)
        self._max_queue = int(FLAGS["serving_max_queue"]
                              if max_queue is None
                              else max_queue)  # guarded-by: _cond
        # drain-per-batch mode (continuous=False) exists ONLY as the
        # honest A/B baseline for decode_bench — same engine, same
        # compiled shapes, admission gated on an empty batch
        self._continuous = bool(continuous)
        # prefix caching + reservation policy (ISSUE 13). demand mode
        # reserves the prompt's pages plus kv_decode_headroom pages at
        # admission and grows mid-decode (preempting when the pool runs
        # dry); worst_case is the PR 6 reserve-everything policy, kept
        # as the bench's admitted-concurrency baseline
        self._prefix_on = bool(FLAGS["prefix_cache"]
                               if prefix_cache is None else prefix_cache)
        reservation = str(FLAGS["kv_reservation"]
                          if reservation is None else reservation)
        if reservation not in ("demand", "worst_case"):
            raise ValueError(
                f"reservation must be 'demand' or 'worst_case', "
                f"got {reservation!r}")
        self._reservation = reservation
        self._headroom_pages = max(0, int(FLAGS["kv_decode_headroom"]))
        self.cache = PagedKvCache(
            spec.n_layers, spec.n_kv_heads, spec.head_dim,
            page_size=ps, num_pages=npages,
            label=f"{self.name}.v{self.version}",
            prefix_cache=self._prefix_on,
            mesh=self._mesh, shard_spec=self._pool_spec())
        # host refuge for preempted sequences' pages (kv_spill_dir
        # moves it to disk); cleared at retirement — leaks nothing
        self._spill = HostSpillStore(
            spill_dir=spill_dir, label=f"{self.name}.v{self.version}")
        w_max = self.cache.allocator.pages_for_tokens(self.max_seq_len)
        self._width_ladder = width_ladder(w_max)
        # chunked prefill (ISSUE 10): the per-step prompt-token budget
        # AND the compiled chunk width. A PR 8 tunable: the FLAGS
        # constant is the cold default, the autotune cache overrides
        # per device kind (decode_bench seeds it via measure-or-model
        # and the observed prompt-length histogram). Clamped to the
        # longest admissible prompt (max_seq_len - 1: max_new >= 1) —
        # a wider chunk than any prompt only burns warm compiles.
        # Resolved ONCE, before warm(), like every other ladder knob.
        chunk = int(effective_flag("prefill_chunk")
                    if prefill_chunk is None else prefill_chunk)
        self._prefill_chunk = max(1, min(chunk, max(1,
                                                    self.max_seq_len - 1)))
        # the third padded dimension of the compiled step: pure-decode
        # steps ride the C=1 shapes (exactly the PR 6 step — chunking
        # costs nothing when no prompt is in flight), steps carrying a
        # prefill grant ride the C=chunk shapes
        self._chunk_ladder = sorted({1, self._prefill_chunk})
        # speculative decoding (ISSUE 14): a small DRAFT decoder
        # proposes spec_k tokens per decoding slot per round; the
        # target verifies all k+1 positions in ONE chunked call. The
        # draft's KV pool MIRRORS the target's page geometry — same
        # allocator, same page ids, same tables — so reservation,
        # rollback, COW, spill and restore stay ONE mechanism. spec_k
        # resolves like every ladder knob: explicit arg, else the
        # autotune cache through effective_flag ('spec_k'), else the
        # FLAGS cold default (0 = off, bit-identical old behavior).
        if isinstance(draft_spec, dict):
            draft_spec = DecoderSpec.from_dict(draft_spec)
        k_spec = int(effective_flag("spec_k")
                     if spec_k is None else spec_k)
        if k_spec < 0:
            raise ValueError(f"spec_k must be >= 0, got {k_spec}")
        if k_spec > 0 and draft_spec is None and spec_k is not None:
            # only an EXPLICIT spec_k without a draft is a caller error;
            # a flag/autotune-sourced value must not refuse plain
            # deploys fleet-wide once a nonzero winner is persisted —
            # engines without a draft are always off (flags.py)
            raise ValueError(
                f"spec_k {k_spec} needs a draft decoder — pass "
                "draft_spec (or draft_checkpoint_dir through the "
                "server)")
        if draft_spec is not None:
            validate_draft_spec(spec, draft_spec)
            if self._mesh is not None:
                self._check_kv_divisible("draft", draft_spec)
        if draft_spec is None:
            k_spec = 0
        # the verify chunk writes through pos + k: never past the
        # sequence cap (k_eff clamps per slot; this bounds the ladder)
        self._spec_k = max(0, min(k_spec, self.max_seq_len - 2))
        self._draft_spec = draft_spec if self._spec_k else None
        if self._spec_k:
            self._verify_lanes = self._spec_k + 1
            # draft calls: C=1 singles, a <= 2-lane catch-up chunk
            # after a fully-accepted round, and the prefill chunks it
            # shadows
            self._draft_chunk_ladder = sorted(
                {1, 2, self._prefill_chunk})
            self._draft_params = (
                build_decoder_params(draft_spec)
                if draft_params is None
                else draft_params)  # guarded-by: _step_mu
            if self._mesh is not None:
                from ..mesh import shard_param_tree

                self._draft_params = shard_param_tree(
                    self._draft_params, self._mesh, self._mesh_rules)
            self._draft_cache = PagedKvCache(
                draft_spec.n_layers, draft_spec.n_kv_heads,
                draft_spec.head_dim, page_size=ps, num_pages=npages,
                allocator=self.cache.allocator,
                mesh=self._mesh,
                shard_spec=self._pool_spec())  # guarded-by: _step_mu
        else:
            self._verify_lanes = 0
            self._draft_chunk_ladder = []
            self._draft_params = None  # guarded-by: _step_mu
            self._draft_cache = None  # guarded-by: _step_mu
        # embeddings/scoring lane (ISSUE 20): opt-in because it warms
        # its own all-lane compiled family (slots x widths x chunks) —
        # engines that never score must not pay those compiles
        self._embed_on = bool(embeddings)
        self._cond = threading.Condition()
        self._queue: List[_DecodeRequest] = []  # guarded-by: _cond
        self._slots: List[_Slot] = []  # guarded-by: _cond
        self._embed_queue: List[_EmbedRequest] = []  # guarded-by: _cond
        self._embed_slots: List[_EmbedSlot] = []  # guarded-by: _cond
        self._stopping = False  # guarded-by: _cond
        self._released = False  # guarded-by: _cond
        self._seq_counter = 0  # guarded-by: _cond
        self._n_requests = 0  # guarded-by: _cond
        self._n_steps = 0  # guarded-by: _cond
        self._compiled_shapes: set = set()  # guarded-by: _step_mu
        self._g_depth = _metrics.gauge(
            f"serving.decode.queue_depth.{self.name}.v{self.version}")
        # per-instance for the same reason as queue_depth: a draining
        # old version must not clobber the live engine's value
        self._g_live = _metrics.gauge(
            f"serving.decode.live_slots.{self.name}.v{self.version}")
        # embed occupancy is its OWN gauge: embeddings completing with
        # live_slots untouched is the zero-decode-slot proof
        self._g_embed = _metrics.gauge(
            f"serving.decode.embed_slots.{self.name}.v{self.version}")

        import jax

        spec_ref = spec  # closed over; jit retraces only on shape change

        def _step(params, tokens, positions, q_lens, k_pool, v_pool,
                  tables, lens):
            return decoder_step_chunked(params, spec_ref, tokens,
                                        positions, q_lens, k_pool,
                                        v_pool, tables, lens)

        # donate the pools on TPU so XLA updates the KV pages in place
        # (HBM footprint stays the preallocated pool); CPU ignores
        # donation, so skip it there to avoid per-call warnings
        donate = (bool(FLAGS["donate_state"])
                  and jax.default_backend() == "tpu")
        self._donate = donate
        step_out_shardings = None
        if self._mesh is not None:
            # pin the step outputs: pools keep the kv-head sharding they
            # came in with, logits come back replicated (the scheduler
            # samples host-side). Without the pin GSPMD may choose a
            # different output layout per shape and the next step's
            # input sharding drift would mint a post-warm compile.
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as _P

            pool_sh = NamedSharding(self._mesh, self._pool_spec())
            step_out_shardings = (pool_sh, pool_sh,
                                  NamedSharding(self._mesh, _P()))
        self._step_out_shardings = step_out_shardings
        self._step_fn = jax.jit(
            _step,
            donate_argnums=(4, 5) if donate else (),
            **({"out_shardings": step_out_shardings}
               if step_out_shardings is not None
               else {}))  # guarded-by: _step_mu
        if self._spec_k:
            draft_ref = self._draft_spec

            def _verify(params, tokens, positions, q_lens, k_pool,
                        v_pool, tables, lens):
                return decoder_step_chunked(params, spec_ref, tokens,
                                            positions, q_lens, k_pool,
                                            v_pool, tables, lens,
                                            all_lanes=True)

            def _draft(params, tokens, positions, q_lens, k_pool,
                       v_pool, tables, lens):
                return decoder_step_chunked(params, draft_ref, tokens,
                                            positions, q_lens, k_pool,
                                            v_pool, tables, lens)

            _sharded_kw = ({"out_shardings": step_out_shardings}
                           if step_out_shardings is not None else {})
            self._verify_fn = jax.jit(
                _verify,
                donate_argnums=(4, 5) if donate
                else (), **_sharded_kw)  # guarded-by: _step_mu
            self._draft_fn = jax.jit(
                _draft,
                donate_argnums=(4, 5) if donate
                else (), **_sharded_kw)  # guarded-by: _step_mu
        else:
            self._verify_fn = None  # guarded-by: _step_mu
            self._draft_fn = None  # guarded-by: _step_mu
        if self._embed_on:
            def _embed(params, tokens, positions, q_lens, k_pool,
                       v_pool, tables, lens):
                return decoder_step_chunked(params, spec_ref, tokens,
                                            positions, q_lens, k_pool,
                                            v_pool, tables, lens,
                                            all_lanes=True,
                                            return_hidden=True)

            embed_out = None
            if step_out_shardings is not None:
                from jax.sharding import NamedSharding as _NS
                from jax.sharding import PartitionSpec as _PS

                # hidden states replicate like logits: pooling and
                # logprob scoring are host-side
                embed_out = step_out_shardings + (
                    _NS(self._mesh, _PS()),)
            self._embed_fn = jax.jit(
                _embed,
                donate_argnums=(4, 5) if donate else (),
                **({"out_shardings": embed_out}
                   if embed_out is not None
                   else {}))  # guarded-by: _step_mu
        else:
            self._embed_fn = None  # guarded-by: _step_mu
        # serializes warm() (caller thread) against live steps (the
        # scheduler thread): read-pools -> step -> rebind must be
        # atomic or concurrent rebinds silently drop KV writes
        self._step_mu = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"decode-{self.name}-v{self.version}")
        self._thread.start()
        if warm:
            try:
                self.warm()
            except BaseException:
                # failed warm is the registry's rollback path: the
                # scheduler thread (and the params/pools it pins) must
                # not outlive the failed deploy
                self.stop(drain=False)
                raise

    # -- public surface ---------------------------------------------------
    @property
    def slot_ladder(self) -> List[int]:
        return list(self._slot_ladder)

    @property
    def table_width_ladder(self) -> List[int]:
        return list(self._width_ladder)

    @property
    def prefill_chunk(self) -> int:
        return self._prefill_chunk

    @property
    def chunk_ladder(self) -> List[int]:
        return list(self._chunk_ladder)

    @property
    def spec_k(self) -> int:
        """Draft proposals per decoding slot per round (0 = speculation
        off — no draft loaded, bit-identical non-speculative decode)."""
        return self._spec_k

    @property
    def draft_spec(self) -> Optional[DecoderSpec]:
        return self._draft_spec

    @property
    def mesh_spec(self):
        """The MeshSpec this engine spans (None = single-chip)."""
        return self._mesh_spec

    @staticmethod
    def _kv_pool_axes(rules):
        """The mesh axes sharding the KV-HEAD dim of the paged pool:
        whatever the rules put on the COLUMN dim of the K projection
        (wk's columns reshape to [kv_heads, head_dim], so a tp-sharded
        wk writes tp-sharded kv heads — the pool must shard the same
        way or every step pays a reshard)."""
        spec = tuple(rules.spec_for("layer0/wk", 2))
        entry = spec[1] if len(spec) > 1 else None
        if entry is None:
            return None
        return entry if isinstance(entry, tuple) else (str(entry),)

    def _kv_shard_degree(self) -> int:
        if not self._kv_head_axes:
            return 1
        import numpy as _np

        for a in self._kv_head_axes:
            # typed here: axis_size would KeyError from deep inside
            # construction, breaking the ValueError discipline every
            # other load_decoder misconfiguration follows
            if a not in self._mesh_spec:
                raise ValueError(
                    f"decoder rules shard kv heads over axis {a!r}, "
                    f"which mesh {self._mesh_spec} does not have — add "
                    "the axis or pass matching mesh_rules")
        return int(_np.prod([self._mesh_spec.axis_size(a)
                             for a in self._kv_head_axes]))

    def _check_kv_divisible(self, what: str, spec: DecoderSpec):
        deg = self._kv_shard_degree()
        if deg > 1 and spec.n_kv_heads % deg:
            raise ValueError(
                f"{what} decoder has {spec.n_kv_heads} kv heads, not "
                f"divisible by the mesh kv-head shard degree {deg} "
                f"(axes {self._kv_head_axes} of {self._mesh_spec}) — "
                "resize the mesh or the model's kv heads")

    def _pool_spec(self):
        """PartitionSpec of the paged pools ([layers, pages, page_size,
        kv_heads, head_dim] — kv-head axis sharded, the rest
        replicated); None when unsharded."""
        if self._mesh is None:
            return None
        import jax.sharding as _shd

        ax = self._kv_head_axes
        return _shd.PartitionSpec(
            None, None, None,
            (ax if ax is None or len(ax) > 1 else ax[0]), None)

    def warm(self):
        """Pre-compile EVERY (slot-count, table-width, chunk) triple on
        an all-dead synthetic batch (writes land on the garbage page).
        After this, sequence churn at ragged lengths — prefill chunks
        included — compiles nothing: all three padded dimensions only
        ever take ladder values. With a speculative draft attached
        (ISSUE 14) the chunk ladder grows its ``spec_k + 1`` VERIFY
        entry (the all-lane-logits form) and the draft's own compiled
        ladder ({1, 2, chunk} — singles, the post-full-accept catch-up
        chunk, and the prefill chunks it shadows) warms alongside, so a
        speculative churn still performs zero post-warm compiles."""
        with _tracing.span("serving.decode.warmup", model=self.name,
                           version=self.version):
            for s in self._slot_ladder:
                for w in self._width_ladder:
                    def dead(c):
                        return (np.zeros((s, c), np.int32),
                                np.zeros((s, c), np.int32),
                                np.zeros(s, np.int32),
                                np.full((s, w), GARBAGE_PAGE, np.int32),
                                np.zeros(s, np.int32))

                    for c in self._chunk_ladder:
                        self._run_step_arrays(*dead(c))
                    if self._spec_k:
                        self._run_verify_arrays(*dead(self._verify_lanes))
                        for c in self._draft_chunk_ladder:
                            self._run_draft_arrays(*dead(c))
                    if self._embed_on:
                        # the embed lane's all-lane+hidden family warms
                        # over the same triples — a mixed churn of
                        # generate + embeddings compiles nothing
                        for c in self._chunk_ladder:
                            self._run_embed_arrays(*dead(c))

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               temperature: float = 0.0, top_k: int = 0,
               seed: int = 0, mask: Optional[Any] = None,
               topk_first: int = 0) -> _DecodeRequest:
        """Validate + reserve KV pages + enqueue. All refusals are
        synchronous and typed: ``ServerOverloaded`` (queue full OR page
        pool exhausted), ``RequestTooLarge`` (can't ever fit),
        ``EngineRetired``, ``ValueError`` (bad tokens / bad sampling
        params). ``temperature``/``top_k``/``seed`` select the sampling
        policy per request (``sample_token``; 0.0 = greedy).

        ``mask`` (ISSUE 20) constrains generation to a
        ``TokenMaskSpec`` language (spec object or its wire dict): the
        automaton's allowed-set zeroes disallowed logits BEFORE the
        per-(seed, position) choice, so constrained output is exactly
        as deterministic and batch-composition-independent as
        unconstrained. The sequence finishes early when the automaton
        has no further transition. ``topk_first`` asks for the first
        generated position's top-k token order in the result
        (``first_topk``) — the beam fork point."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if int(prompt.min()) < 0 or int(prompt.max()) >= self.spec.vocab:
            raise ValueError(
                f"prompt token ids must be in [0, {self.spec.vocab})")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = int(prompt.size) + max_new
        if total > self.max_seq_len:
            raise RequestTooLarge(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) = "
                f"{total} exceeds max_seq_len {self.max_seq_len}")
        if self._reservation == "demand" and \
                self.cache.allocator.pages_for_tokens(total) > \
                self.cache.num_pages - 1:
            # demand mode admits beyond the worst case, so the ONLY
            # hard bound is "could this sequence fit even alone, with
            # everyone else preempted" — refuse up front if not (the
            # growth path's progress guarantee depends on it)
            raise RequestTooLarge(
                f"worst case {total} tokens = "
                f"{self.cache.allocator.pages_for_tokens(total)} pages "
                f"exceeds the whole pool "
                f"({self.cache.num_pages - 1} usable pages)")
        temperature = float(temperature)
        top_k = int(top_k)
        if temperature < 0.0 or not math.isfinite(temperature):
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        topk_first = int(topk_first)
        if topk_first < 0 or topk_first > self.spec.vocab:
            raise ValueError(
                f"topk_first must be in [0, {self.spec.vocab}], got "
                f"{topk_first}")
        automaton = None
        if mask is not None:
            from .workloads.masks import MaskAutomaton, TokenMaskSpec

            if isinstance(mask, dict):
                mask = TokenMaskSpec.from_dict(mask)
            if isinstance(mask, TokenMaskSpec):
                automaton = mask.compile()
            elif isinstance(mask, MaskAutomaton):
                automaton = mask
            else:
                raise ValueError(
                    f"mask must be a TokenMaskSpec, its wire dict, or "
                    f"a MaskAutomaton, got {type(mask).__name__}")
            if automaton.max_token() >= self.spec.vocab:
                raise ValueError(
                    f"mask names token id {automaton.max_token()}, "
                    f"outside this decoder's vocab "
                    f"[0, {self.spec.vocab})")
            if not automaton.allowed(automaton.start,
                                     self.spec.vocab).any():
                raise ValueError("mask allows no first token")
        deadline = (None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1e3)
        with self._cond:
            if self._stopping:
                raise EngineRetired(
                    f"decoder '{self.name}' v{self.version} is retiring")
            if len(self._queue) >= self._max_queue:
                _m_overloads.inc()
                raise ServerOverloaded(
                    f"decoder '{self.name}' queue is full "
                    f"({self._max_queue} deep)")
            self._seq_counter += 1
            seq_id = self._seq_counter
            try:
                # reserve NOW: worst_case mode takes the whole
                # prompt+max_new bound (an admitted sequence can then
                # never die of exhaustion); demand mode takes only the
                # prompt plus a small decode headroom — growth and
                # preemption own the tail (ISSUE 13). Either way the
                # pool is the admission bound (kv_cache.py) and the
                # refusal is typed and side-effect-free.
                res = self._reserve_locked(seq_id, prompt, total)
            except ServerOverloaded:
                _m_overloads.inc()
                raise
            req = _DecodeRequest(prompt, max_new, deadline, seq_id,
                                 temperature=temperature, top_k=top_k,
                                 seed=seed, mask=automaton,
                                 want_topk=topk_first)
            req.cached_tokens = res["cached_tokens"]
            req.cow = res["cow"]
            self._queue.append(req)
            self._n_requests += 1
            self._g_depth.set(len(self._queue))
            # instantaneous concurrency demand — what slots="auto"
            # derives its ladder from (observed outside the lock)
            demand = len(self._queue) + len(self._slots)
            self._cond.notify()
        _observe_shape("decode_slots", demand)
        # the prompt-length histogram the prefill_chunk tuner derives
        # its crossover from (bench sessions seed it, ISSUE 10)
        _observe_shape("prefill_chunk", int(prompt.size))
        _m_requests.inc()
        return req

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None,
                 timeout: float = 300.0, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0,
                 mask: Optional[Any] = None,
                 topk_first: int = 0) -> Dict[str, Any]:
        """Blocking convenience: submit + wait. Returns
        ``{"tokens": [...], "prompt_len": n, "version": v,
        "steps_to_first_token": k}``.
        ``temperature``/``top_k``/``seed`` thread through to the
        per-request sampler (0.0 = greedy, the default);
        ``mask``/``topk_first`` to the workload layer (ISSUE 20)."""
        req = self.submit(prompt, max_new_tokens, deadline_ms=deadline_ms,
                          temperature=temperature, top_k=top_k, seed=seed,
                          mask=mask, topk_first=topk_first)
        if not req.ev.wait(timeout):
            # withdraw before raising: an abandoned sequence must not
            # keep its page reservation or burn further decode steps.
            # cancel() returning False means the request finished in
            # the wait-vs-cancel window — deliver that result, don't
            # discard paid-for tokens as a timeout
            if self.cancel(req):
                raise ServingError(
                    f"generate on '{self.name}' timed out after "
                    f"{timeout}s (decode scheduler wedged?)")
        if req.error is not None:
            raise req.error
        return req.result

    @property
    def embeddings_enabled(self) -> bool:
        return self._embed_on

    @property
    def prefix_cache_enabled(self) -> bool:
        return self._prefix_on

    def submit_embed(self, prompt: Sequence[int],
                     deadline_ms: Optional[float] = None
                     ) -> _EmbedRequest:
        """Enqueue a prompt-only embedding/scoring request (ISSUE 20).
        Reservation is the reserve-at-admission math with
        ``max_new = 0``: exactly the prompt's pages, taken NOW, typed
        ``ServerOverloaded`` on refusal. The request rides the chunked
        prefill path in the embed lane and never holds a decode
        slot."""
        if not self._embed_on:
            raise ServingError(
                f"decoder '{self.name}' was loaded without "
                "embeddings=True — the embed lane's compiled shapes "
                "are not warmed")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if int(prompt.min()) < 0 or int(prompt.max()) >= self.spec.vocab:
            raise ValueError(
                f"prompt token ids must be in [0, {self.spec.vocab})")
        if int(prompt.size) > self.max_seq_len:
            raise RequestTooLarge(
                f"prompt ({prompt.size}) exceeds max_seq_len "
                f"{self.max_seq_len}")
        deadline = (None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1e3)
        with self._cond:
            if self._stopping:
                raise EngineRetired(
                    f"decoder '{self.name}' v{self.version} is retiring")
            if len(self._embed_queue) >= self._max_queue:
                _m_overloads.inc()
                raise ServerOverloaded(
                    f"decoder '{self.name}' embed queue is full "
                    f"({self._max_queue} deep)")
            self._seq_counter += 1
            seq_id = self._seq_counter
            try:
                self.cache.allocator.alloc(seq_id, int(prompt.size))
            except ServerOverloaded:
                _m_overloads.inc()
                raise
            req = _EmbedRequest(prompt, deadline, seq_id,
                                self.spec.d_model)
            self._embed_queue.append(req)
            self._n_requests += 1
            self._cond.notify()
        _observe_shape("prefill_chunk", int(prompt.size))
        _m_embed_requests.inc()
        return req

    def embed(self, prompt: Sequence[int],
              deadline_ms: Optional[float] = None,
              timeout: float = 300.0) -> Dict[str, Any]:
        """Blocking convenience: submit_embed + wait. Returns
        ``{"embedding": [d_model floats] (mean-pooled final hidden
        states), "logprobs": [P-1 floats] (position p scores
        prompt[p+1]), "prompt_len": P, "version": v, "steps": n}``."""
        req = self.submit_embed(prompt, deadline_ms=deadline_ms)
        if not req.ev.wait(timeout):
            if self.cancel(req):
                raise ServingError(
                    f"embed on '{self.name}' timed out after "
                    f"{timeout}s (decode scheduler wedged?)")
        if req.error is not None:
            raise req.error
        return req.result

    def cancel(self, req: _DecodeRequest,
               msg: str = "abandoned by caller") -> bool:
        """Withdraw a submitted request whose waiter gave up: frees its
        KV pages now and fails it, so the scheduler drops the slot at
        the next answer phase instead of decoding dead work to
        completion. A step already in flight still writes through the
        page table it captured BEFORE the free — safe today because a
        re-allocated page's every position is rewritten by its new
        owner in the same step that first attends to it
        (write-before-attend); the NEXT table build degrades the
        canceled row to the garbage page. Returns False if the
        request already finished."""
        with self._cond:
            if req.ev.is_set():
                return False
            if isinstance(req, _EmbedRequest):
                if req in self._embed_queue:
                    self._embed_queue.remove(req)
            elif req in self._queue:
                self._queue.remove(req)
                self._g_depth.set(len(self._queue))
            _m_cancels.inc()
            self._fail_locked(req, ServingError(
                f"generate on '{self.name}' canceled: {msg}"))
            self._cond.notify_all()
            return True

    def stream_tokens(self, req: _DecodeRequest, offset: int,
                      timeout: float = 30.0) -> Dict[str, Any]:
        """Incremental token read for streaming generate (ISSUE 12):
        block until the sequence has tokens past ``offset`` (or it
        finished / failed / the wait lapses), then return everything
        past it. A PURE FUNCTION of (request state, offset) — it never
        advances hidden cursor state — which is what makes a
        retransmitted stream frame safe to answer from the dedup cache
        OR by re-execution: either way the client gets exactly the
        tokens at those offsets, with zero extra decode steps.

        Returns ``{"tokens", "offset", "next_offset", "done"}`` plus
        ``"result"`` once done; a failed request re-raises its typed
        error (DeadlineExceeded, EngineRetired, ...). A timeout with no
        new tokens returns an empty chunk with ``done=False`` — the
        caller polls again."""
        offset = int(offset)
        if offset < 0:
            raise ValueError(f"stream offset must be >= 0, got {offset}")
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            while len(req.produced) <= offset and not req.ev.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                # lint: allow-blocking — a bounded reader wait on the
                # engine's own condition; the answer phase notifies on
                # every step that produced a token
                self._cond.wait(remaining)
            toks = [int(t) for t in req.produced[offset:]]
            done = req.ev.is_set()
            err = req.error
            result = req.result
        if done and err is not None:
            raise err
        out: Dict[str, Any] = {"tokens": toks, "offset": offset,
                               "next_offset": offset + len(toks),
                               "done": done}
        if done:
            out["result"] = result
        return out

    def set_max_queue(self, n: int):
        with self._cond:
            self._max_queue = max(1, int(n))

    def stop(self, drain: bool = True, timeout: float = 300.0):
        """Refuse new work; ``drain`` completes every admitted AND
        queued sequence first (the hot-swap drain guarantee), else all
        are failed with EngineRetired. Then params/pools/compiled steps
        are dropped so retirement releases the executables and HBM."""
        with self._cond:
            self._stopping = True
            if not drain:
                for r in self._queue:
                    self._fail_locked(r, EngineRetired(
                        f"decoder '{self.name}' v{self.version} unloaded"))
                self._queue.clear()
                for r in self._embed_queue:
                    self._fail_locked(r, EngineRetired(
                        f"decoder '{self.name}' v{self.version} unloaded"))
                self._embed_queue.clear()
                for s in self._embed_slots:
                    if not s.req.ev.is_set():
                        self._fail_locked(s.req, EngineRetired(
                            f"decoder '{self.name}' v{self.version} "
                            "unloaded"))
                    else:
                        self.cache.allocator.free(s.req.seq_id)
                self._embed_slots = []
                for s in self._slots:
                    # a slot _complete()d mid-step may still be in
                    # _slots (removal happens under _cond after the
                    # step) — never overwrite a delivered result
                    if not s.req.ev.is_set():
                        self._fail_locked(s.req, EngineRetired(
                            f"decoder '{self.name}' v{self.version} "
                            "unloaded"))
                    else:
                        self.cache.allocator.free(s.req.seq_id)
                self._slots = []
                self._g_depth.set(0)
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():  # pragma: no cover - wedged scheduler
            _log.error("decode scheduler for %s v%d did not exit in %.0fs",
                       self.name, self.version, timeout)
        # params/step/pools drop under _step_mu — THEIR guard (guards-lint
        # finding: they used to drop under _cond while _run_step_arrays
        # reads them under _step_mu; safe only by join-ordering, which a
        # static model can't see and a future warm()-after-stop wouldn't
        # honor)
        with self._step_mu:
            self._params = None
            self._step_fn = None
            self._embed_fn = None
            self._draft_params = None
            self._verify_fn = None
            self._draft_fn = None
            if self._draft_cache is not None:
                # shared allocator: retire() is idempotent, the draft
                # pool's HBM frees with its own k/v drop
                self._draft_cache.release()
                self._draft_cache = None
            self.cache.release()
        # any spills that survived the drain (preempted sequences the
        # retirement failed) die with the engine — files included
        self._spill.clear()
        with self._cond:
            self._released = True
            self._g_depth.set(0)
            # the scheduler may exit between steps without a final
            # answer phase — a retired engine must not report phantom
            # live slots
            self._g_live.set(0)
            self._g_embed.set(0)

    def stats(self) -> Dict[str, Any]:
        # _compiled_shapes is _step_mu state: snapshot it under ITS lock
        # (guards-lint finding — sorted() here used to iterate the set
        # under _cond while the scheduler's _run_step_arrays add()ed to
        # it under _step_mu: a mid-iteration mutation raises
        # "Set changed size during iteration" on a stats scrape)
        with self._step_mu:
            shapes = sorted(self._compiled_shapes)
        with self._cond:
            return {
                "name": self.name,
                "version": self.version,
                "kind": self.kind,
                "spec": self.spec.to_dict(),
                "slots": list(self._slot_ladder),
                "table_widths": list(self._width_ladder),
                "prefill_chunk": self._prefill_chunk,
                "chunk_ladder": list(self._chunk_ladder),
                "page_size": self.cache.page_size,
                "max_seq_len": self.max_seq_len,
                "continuous": self._continuous,
                "reservation": self._reservation,
                "spec_k": self._spec_k,
                "mesh": (dict(self._mesh_spec.axes)
                         if self._mesh_spec is not None else None),
                "draft": (self._draft_spec.to_dict()
                          if self._draft_spec is not None else None),
                "prefix_cache": self._prefix_on,
                "prefix": self.cache.allocator.prefix_stats(),
                "spilled_sequences": self._spill.count(),
                "kv": self.cache.allocator.stats(),
                "queue_depth": len(self._queue),
                "live": len(self._slots),
                "embeddings": self._embed_on,
                "embed_queue": len(self._embed_queue),
                "live_embed": len(self._embed_slots),
                "max_queue": self._max_queue,
                "requests": self._n_requests,
                "steps": self._n_steps,
                "compiled_shapes": shapes,
                "stopping": self._stopping,
            }

    # -- scheduler --------------------------------------------------------
    def _reserve_locked(self, seq_id: int, prompt, total: int
                        ) -> Dict[str, Any]:
        """One reservation under the engine's policy: demand = prompt
        pages + decode headroom (capped at the worst case), worst_case
        = everything. Prefix caching maps the cached chain read-only
        either way. Raises ``ServerOverloaded`` side-effect-free."""
        if self._reservation == "demand":
            reserve = min(total, len(prompt)
                          + self._headroom_pages * self.cache.page_size)
        else:
            reserve = total
        if self._prefix_on:
            return self.cache.allocator.alloc_prefix(seq_id, prompt,
                                                     reserve)
        self.cache.allocator.alloc(seq_id, reserve)
        return {"cached_tokens": 0, "cow": None}

    def _fail_locked(self, req: _DecodeRequest, err: BaseException):
        self.cache.allocator.free(req.seq_id)
        if req.cow is not None:
            # the COW source pin must not outlive the request (a pinned
            # entry is un-evictable)
            self.cache.allocator.release_cow(req.cow["key"])
            req.cow = None
        # a preempted request's host spill dies with it — cancel/
        # deadline/retirement mid-preemption leaks nothing
        self._spill.drop(req.seq_id)
        req.fail(err)

    def _drop_expired_locked(self, now: float):
        keep = []
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                _m_deadline_miss.inc()
                self._fail_locked(r, DeadlineExceeded(
                    f"request to decoder '{self.name}' missed its "
                    "deadline while queued"))
            else:
                keep.append(r)
        if len(keep) != len(self._queue):
            self._queue[:] = keep
            self._g_depth.set(len(keep))
        ekeep = []
        for r in self._embed_queue:
            if r.deadline is not None and now > r.deadline:
                _m_deadline_miss.inc()
                self._fail_locked(r, DeadlineExceeded(
                    f"request to decoder '{self.name}' missed its "
                    "deadline while queued"))
            else:
                ekeep.append(r)
        if len(ekeep) != len(self._embed_queue):
            self._embed_queue[:] = ekeep

    def _admit_locked(self):
        """Move queued requests into free slots. Continuous mode admits
        whenever a slot is free — INTO the in-flight batch; drain mode
        (the bench baseline) only refills an empty batch. A request
        whose reservation was surrendered (preempted victims sit at the
        queue FRONT, demoted reservations wherever they were) must
        re-reserve first; a refusal leaves it queued — completions and
        cache evictions free the pages it is waiting for."""
        if not self._continuous and self._slots:
            return
        while self._queue and len(self._slots) < self._max_slots:
            req = self._queue[0]
            if req.ev.is_set():
                # canceled / expired while queued — already failed
                self._queue.pop(0)
                continue
            if req.needs_alloc:
                total = len(req.prompt) + req.max_new
                try:
                    if req.resume_pos is not None:
                        # restore-before-step: cover what was spilled
                        # plus the decode headroom; prefix matching is
                        # deliberately NOT consulted — the spill is the
                        # bitwise truth (preempt-never-corrupts)
                        reserve = min(total, max(req.resume_pos, 1)
                                      + self._headroom_pages
                                      * self.cache.page_size)
                        self.cache.allocator.alloc(req.seq_id, reserve)
                    else:
                        res = self._reserve_locked(req.seq_id,
                                                   req.prompt, total)
                        req.cached_tokens = res["cached_tokens"]
                        req.cow = res["cow"]
                except ServerOverloaded:
                    break
                req.needs_alloc = False
            self._queue.pop(0)
            slot = _Slot(req,
                         self.cache.allocator.held_pages(req.seq_id))
            if req.resume_pos is not None:
                slot.pos = req.resume_pos
                # the draft pool restores from the same spill; its
                # watermark resumes where preemption froze it
                slot.dpos = (req.resume_dpos
                             if req.resume_dpos is not None
                             else req.resume_pos)
                slot.pending_restore = True
                req.resume_pos = None
                req.resume_dpos = None
            else:
                # cached prompt pages are already written (and mapped):
                # prefill starts at the first uncached token — in BOTH
                # pools (the publisher's draft prefilled the same
                # pages; the COW copy below covers the tail likewise)
                slot.pos = req.cached_tokens
                slot.dpos = req.cached_tokens
            slot.steps = req.carry_steps
            slot.first_token_steps = req.carry_fts
            self._slots.append(slot)
            _m_admitted.inc()
            _m_queue_wait.observe((time.monotonic() - req.t_enq) * 1e3)
        # embed admission: its own slot lane, capped by the same ladder
        # max — decode slots and live_slots are untouched. Reservation
        # happened at submit (the prompt's pages, never grown), so
        # admission is pure bookkeeping.
        while self._embed_queue and \
                len(self._embed_slots) < self._max_slots:
            ereq = self._embed_queue.pop(0)
            if ereq.ev.is_set():
                continue
            self._embed_slots.append(_EmbedSlot(
                ereq, self.cache.allocator.held_pages(ereq.seq_id)))
            _m_admitted.inc()
            _m_queue_wait.observe((time.monotonic() - ereq.t_enq) * 1e3)
        self._g_depth.set(len(self._queue))
        self._g_live.set(len(self._slots))
        self._g_embed.set(len(self._embed_slots))

    def _next_live(self
                   ) -> Optional[Tuple[List[_Slot], List[_EmbedSlot]]]:
        # lint: allow-blocking — Condition.wait on the engine's own
        # condition is the scheduler's idle state by design
        with self._cond:
            while True:
                self._drop_expired_locked(time.monotonic())
                self._admit_locked()
                if self._slots or self._embed_slots:
                    return list(self._slots), list(self._embed_slots)
                if self._stopping and not self._queue \
                        and not self._embed_queue:
                    return None
                # no live slots here implies the queues are (almost
                # always) empty too — admission can't fail with every
                # slot free — so idle blocks untimed on submit()/stop()
                # notifies instead of polling 20x/s per loaded decoder;
                # the timed wait survives only for the defensive case
                # of a non-empty queue, whose deadlines need the poll
                self._cond.wait(0.05 if (self._queue
                                         or self._embed_queue)
                                else None)

    def _loop(self):
        while True:
            nxt = self._next_live()
            if nxt is None:
                return
            live, elive = nxt
            try:
                if live:
                    self._step(live)
                if elive:
                    # the embed lane runs AFTER the decode step each
                    # round: decode tokens never stall behind scoring,
                    # and a mixed churn interleaves the two lanes 1:1
                    self._embed_step(elive)
            except BaseException as e:  # a broken step fails ITS slots
                _log.error("decode step on %s v%d failed: %s: %s",
                           self.name, self.version, type(e).__name__, e)
                err = (e if isinstance(e, ServingError) else
                       ServingError(f"{type(e).__name__}: {e}"))
                with self._cond:
                    for s in live + elive:
                        if not s.req.ev.is_set():
                            self._fail_locked(s.req, err)
                    self._slots = [s for s in self._slots
                                   if s not in live]
                    self._embed_slots = [s for s in self._embed_slots
                                         if s not in elive]
                    self._g_live.set(len(self._slots))
                    self._g_embed.set(len(self._embed_slots))
                    if self._donate:
                        # the raising step already consumed the donated
                        # pools — k/v are deleted buffers and every
                        # later step would fail too. Retire: fail
                        # everything, refuse new submits (EngineRetired
                        # -> the server resubmits after a redeploy)
                        # instead of admitting doomed requests.
                        _log.error(
                            "decode pools for %s v%d were donated into "
                            "the failed step — retiring the engine",
                            self.name, self.version)
                        self._stopping = True
                        for s in self._slots + self._embed_slots:
                            if not s.req.ev.is_set():
                                self._fail_locked(s.req, err)
                        self._slots = []
                        self._embed_slots = []
                        for r in self._queue:
                            self._fail_locked(r, err)
                        self._queue.clear()
                        for r in self._embed_queue:
                            self._fail_locked(r, err)
                        self._embed_queue.clear()
                        self._g_depth.set(0)
                        self._g_live.set(0)
                        self._g_embed.set(0)
                        self._cond.notify_all()
                        return

    def _run_step_arrays(self, tokens, positions, q_lens, tables, lens):
        """Shared by warm() and live steps: count a DISTINCT-shape
        compile, run the jitted step, rebind the pools. With a draft
        attached the shape keys carry a model tag ('target'/'verify'/
        'draft') so the three compiled families stay distinct in the
        same churn-pinned set; without one they stay the bare PR 6/9
        triples."""
        with self._step_mu:
            key = (len(tokens), tables.shape[1], tokens.shape[1])
            if self._spec_k or self._embed_on:
                # tagged whenever a second compiled family exists —
                # bare triples and tagged tuples must never mix in one
                # set (stats() sorts it)
                key = ("target",) + key
            if key not in self._compiled_shapes:
                self._compiled_shapes.add(key)
                _m_compiles.inc()
            _m_target_steps.inc()
            k, v, logits = self._step_fn(
                self._params, tokens, positions, q_lens, self.cache.k,
                self.cache.v, tables, lens)
            self.cache.rebind(k, v)
            return logits

    def _run_verify_arrays(self, tokens, positions, q_lens, tables,
                           lens):
        """The speculative-verify target call: same pools, all-lane
        logits ``[B, C, vocab]`` (C = spec_k + 1). One target step
        scores every proposal plus the bonus position."""
        with self._step_mu:
            key = ("verify", len(tokens), tables.shape[1],
                   tokens.shape[1])
            if key not in self._compiled_shapes:
                self._compiled_shapes.add(key)
                _m_compiles.inc()
            _m_target_steps.inc()
            k, v, logits = self._verify_fn(
                self._params, tokens, positions, q_lens, self.cache.k,
                self.cache.v, tables, lens)
            self.cache.rebind(k, v)
            return logits

    def _run_draft_arrays(self, tokens, positions, q_lens, tables,
                          lens):
        """One DRAFT step (propose singles, catch-up chunks, prefill
        shadowing) against the mirrored draft pool — same page tables
        as the target, newest-lane logits."""
        with self._step_mu:
            key = ("draft", len(tokens), tables.shape[1],
                   tokens.shape[1])
            if key not in self._compiled_shapes:
                self._compiled_shapes.add(key)
                _m_compiles.inc()
            _m_draft_steps.inc()
            k, v, logits = self._draft_fn(
                self._draft_params, tokens, positions, q_lens,
                self._draft_cache.k, self._draft_cache.v, tables, lens)
            self._draft_cache.rebind(k, v)
            return logits

    def _run_embed_arrays(self, tokens, positions, q_lens, tables,
                          lens):
        """One EMBED step (ISSUE 20): the all-lane + hidden form
        against the shared target pool — every prompt lane's logits
        ``[B, C, vocab]`` (per-token scoring) and final-norm hidden
        states ``[B, C, d_model]`` (pooling) in one call."""
        with self._step_mu:
            key = ("embed", len(tokens), tables.shape[1],
                   tokens.shape[1])
            if key not in self._compiled_shapes:
                self._compiled_shapes.add(key)
                _m_compiles.inc()
            _m_embed_steps.inc()
            k, v, logits, hidden = self._embed_fn(
                self._params, tokens, positions, q_lens, self.cache.k,
                self.cache.v, tables, lens)
            self.cache.rebind(k, v)
            return logits, hidden

    def _prepare(self, live: List[_Slot]
                 ) -> Tuple[List[_Slot], List[int]]:
        """Pre-step phase (scheduler thread, ISSUE 13): execute pending
        COW copies and preemption restores (device writes, batched,
        under ``_step_mu`` — the same serialization every pool touch
        gets), then grow demand-mode reservations to cover this step's
        grants, preempting/demoting when the pool runs dry. Returns the
        (possibly shrunk) live list and its grants."""
        cows: List[Tuple[int, int]] = []
        restores = []
        spills: Dict[int, Any] = {}
        for s in live:
            if s.pending_restore:
                s.pending_restore = False
                # pop (disk-backed spills np.load) stays outside _cond
                spills[s.req.seq_id] = self._spill.pop(s.req.seq_id)
        with self._cond:
            # request state (cow, pages, spill ownership) is mutated by
            # cancel()/_fail_locked under _cond — read it under _cond
            # too, or a mid-window cancel hands us freed pages / a
            # half-released COW
            for s in live:
                if s.req.ev.is_set():
                    # canceled: pages already freed and any spill
                    # dropped; the popped arrays (if any) die here and
                    # the slot rides one last garbage-table step
                    continue
                spill = spills.get(s.req.seq_id)
                if spill is not None:
                    pages = self.cache.allocator.pages_of(s.req.seq_id)
                    restores.append((pages[:spill[0].shape[1]], spill))
                    _m_restores.inc()
                if s.req.cow is not None:
                    cows.append((s.req.cow["src"], s.req.cow["dst"]))
                    # released before the device copy runs: safe, the
                    # scheduler thread issues every device write, so an
                    # evicted-and-reused src page cannot be rewritten
                    # before copy_pages below reads it
                    self.cache.allocator.release_cow(s.req.cow["key"])
                    s.req.cow = None
        if cows or restores:
            with self._step_mu:
                self.cache.copy_pages(cows)
                if self._draft_cache is not None:
                    # the draft pool mirrors every page move: a COW
                    # tail or restored spill must be valid in BOTH
                    # pools before the slot's next step reads them
                    self._draft_cache.copy_pages(cows)
                for pages, spill in restores:
                    self.cache.scatter_pages(pages, spill[0], spill[1])
                    if self._draft_cache is not None and len(spill) == 4:
                        self._draft_cache.scatter_pages(
                            pages, spill[2], spill[3])
        while True:
            grants = self._grants(live)
            grower = None
            for s, g in zip(live, grants):
                if s.req.ev.is_set():
                    continue  # canceled: pages gone, rides one last
                    # step through the garbage table, answered nowhere
                need = self.cache.allocator.pages_for_tokens(s.pos + g)
                if need > s.pages_held:
                    grower = (s, need - s.pages_held)
                    break
            if grower is None:
                return live, grants
            s, n = grower
            try:
                self.cache.allocator.grow(s.req.seq_id, n)
                s.pages_held += n
                continue
            except ServerOverloaded:
                pass
            if self._reclaim_for_growth(s, live):
                continue
            # nothing reclaimable: the submit-time worst-case-fits-pool
            # check makes this unreachable unless an external allocator
            # user pins pages — fail typed rather than corrupt
            with self._cond:
                if not s.req.ev.is_set():
                    _m_overloads.inc()
                    self._fail_locked(s.req, ServerOverloaded(
                        f"KV pool exhausted mid-decode for seq "
                        f"{s.req.seq_id} with nothing left to preempt "
                        "— external pages pinned?"))
                self._slots = [x for x in self._slots if x is not s]
                self._g_live.set(len(self._slots))
            live = [x for x in live if x is not s]
            if not live:
                return live, []

    def _reclaim_for_growth(self, grower: _Slot,
                            live: List[_Slot]) -> bool:
        """Make pages available for a live slot's growth: demote the
        newest QUEUED reservation first (it has no computed work to
        lose — admission re-reserves it later), else preempt the
        newest live slot other than the grower (spill + requeue at the
        front). Mutates ``live`` in place when it preempts. False =
        nothing left to take."""
        with self._cond:
            for req in reversed(self._queue):
                if req.ev.is_set() or req.needs_alloc:
                    continue
                self.cache.allocator.free(req.seq_id)
                if req.cow is not None:
                    self.cache.allocator.release_cow(req.cow["key"])
                    req.cow = None
                req.cached_tokens = 0
                req.needs_alloc = True
                _m_demotions.inc()
                return True
        victim = None
        for s in reversed(live):
            if s is grower or s.req.ev.is_set():
                continue
            victim = s
            break
        if victim is None:
            return False
        self._preempt(victim)
        live.remove(victim)
        return True

    def _preempt(self, victim: _Slot):
        """Spill the victim's written pages to host (bitwise), free its
        reservation, and requeue it at the FRONT so preemption cannot
        become starvation. Restore scatters the spill into a fresh
        reservation and the page table rebinds — the sequence's K/V
        round-trips exactly (preempt-never-corrupts; reserve-never-dies
        was the PR 6 policy this replaces)."""
        _faults.fire("serving.decode.preempt")
        req = victim.req
        with _tracing.span("serving.decode.preempt", model=self.name,
                           version=self.version, seq=req.seq_id,
                           tokens=victim.pos):
            pages = self.cache.allocator.pages_of(req.seq_id)
            # only ACCEPTED (committed) tokens spill: victim.pos is the
            # post-rollback watermark, so a speculative round's
            # rejected writes are never carried to host
            n_keep = (self.cache.allocator.pages_for_tokens(victim.pos)
                      if victim.pos else 0)
            if n_keep:
                with self._step_mu:
                    arrays = self.cache.gather_pages(pages[:n_keep])
                    if self._draft_cache is not None:
                        arrays = arrays + self._draft_cache.gather_pages(
                            pages[:n_keep])
                # put (disk-backed spills savez) stays outside the
                # step mutex, same as the pop side in _prepare
                self._spill.put(req.seq_id, *arrays)
            self.cache.allocator.free(req.seq_id)
            _m_preemptions.inc()
            with self._cond:
                self._slots = [x for x in self._slots if x is not victim]
                if req.ev.is_set():
                    # canceled/stopped while we spilled: nothing will
                    # resume — drop the spill, leak nothing
                    self._spill.drop(req.seq_id)
                else:
                    req.resume_pos = victim.pos
                    req.resume_dpos = victim.dpos
                    req.carry_steps = victim.steps
                    req.carry_fts = victim.first_token_steps
                    req.needs_alloc = True
                    self._queue.insert(0, req)
                    self._g_depth.set(len(self._queue))
                self._g_live.set(len(self._slots))

    def _k_eff(self, s: _Slot) -> int:
        """Draft proposals this slot can use THIS round: capped by
        spec_k and by how many tokens the sequence may still commit
        (a verify round commits up to k_eff + 1, which must not
        overshoot max_new — so the reservation-bound write at
        ``pos + k_eff`` also never passes the sequence cap)."""
        if not self._spec_k or s.req.ev.is_set() or \
                s.pos < len(s.req.prompt) or s.req.mask is not None:
            # masked requests never ride speculation: the draft
            # proposes from UNMASKED logits, so acceptance would decay
            # to ~0 while still paying the draft steps — and the grant
            # math below assumes plain slots advance one position
            return 0
        total = len(s.req.prompt) + s.req.max_new
        return max(0, min(self._spec_k, total - s.pos - 2))

    def _grants(self, live: List[_Slot]) -> List[int]:
        """Token-budget scheduling (Sarathi-style, ISSUE 10): every
        slot past its prompt gets its one decode token unconditionally
        — in-flight decodes NEVER stall behind a prompt — while slots
        still in prefill share a per-step budget of ``prefill_chunk``
        prompt tokens, granted in slot order. Every prefill slot is
        guaranteed at least one token per step (at ``prefill_chunk=1``
        this is bitwise the PR 6 one-token-per-slot schedule; no slot
        ever starves), so the budget caps the CHUNKS, not progress. A
        solo prompt takes the whole budget every step: P prompt tokens
        cost ceil(P / prefill_chunk) steps instead of P.

        With speculation on (ISSUE 14) a decoding slot's grant is the
        positions its VERIFY chunk writes — ``1 + k_eff`` — so demand-
        mode growth in ``_prepare`` covers the whole speculative write
        range before the round runs; like decode tokens, speculative
        lanes are never budgeted against prefill."""
        budget = self._prefill_chunk
        grants = []
        for s in live:
            remaining_prompt = len(s.req.prompt) - s.pos
            if remaining_prompt > 0:
                g = max(1, min(remaining_prompt, budget))
                budget = max(0, budget - g)
            else:
                g = 1 + self._k_eff(s)
            grants.append(g)
        return grants

    def _choose(self, row, req: _DecodeRequest, position: int) -> int:
        """THE deterministic per-(seed, position) token choice on one
        logits row: greedy argmax at temperature 0, else the seeded
        ``sample_token`` draw. Draft proposals AND the verify
        acceptance walk both use it, so a committed token is always
        exactly what the non-speculative engine would have emitted at
        that position from those logits — spec on/off bitwise equality
        is structural, not statistical (the rejection-sampling
        realization is pinned by (seed, position), ISSUE 14)."""
        if req.temperature <= 0.0:
            return int(np.argmax(row))
        return sample_token(row, req.temperature, req.top_k, req.seed,
                            position)

    def _masked_choice(self, req: _DecodeRequest, row,
                       position: int) -> Tuple[int, bool]:
        """Constrained decode's per-token core (ISSUE 20): zero the
        disallowed lanes to -inf, make THE SAME deterministic
        per-(seed, position) choice the unconstrained path makes, then
        advance the automaton. Masking composes cleanly with the
        sampler — softmax renormalizes over the survivors — so a
        masked request's tokens are a pure function of (seed, mask,
        prompt, params), independent of batch composition (tier-1
        asserts bitwise equality across differently-loaded engines).
        Returns ``(token, exhausted)``; exhausted means the automaton
        has no further transition — the constraint is complete and the
        sequence finishes regardless of max_new."""
        allowed = req.mask.allowed(req.mask_state, self.spec.vocab)
        masked = np.where(allowed, np.asarray(row, np.float64), -np.inf)
        tok = self._choose(masked, req, position)
        ns = req.mask.step(req.mask_state, tok)
        # an allowed token always has a transition; belt-and-braces for
        # a buggy automaton: treat a dead step as exhaustion
        if ns is None:
            return tok, True
        req.mask_state = ns
        _m_masked_tokens.inc()
        return tok, not req.mask.allowed(ns, self.spec.vocab).any()

    def _check_reservation(self, s: _Slot, end_tokens: int):
        """The reservation (grown by _prepare in demand mode) must
        cover every write a step performs. A real raise, not an
        assert: writing through a page index past the reservation
        would corrupt another sequence's pages, and ``python -O``
        strips asserts. Canceled slots are exempt — their pages are
        gone and their table row is all-garbage, so their writes land
        on the garbage page by construction."""
        if not s.req.ev.is_set() and \
                end_tokens > s.pages_held * self.cache.page_size:
            raise ServingError(
                f"chunk grant escaped seq {s.req.seq_id}'s page "
                f"reservation ({end_tokens} tokens > "
                f"{s.pages_held} pages x {self.cache.page_size})")

    def _spec_substep(self, slots: List[_Slot], w_bucket: int
                      ) -> Dict[int, Tuple[List[int], int, int]]:
        """Propose-then-verify for this round's DECODING slots
        (ISSUE 14). The draft runs ``k`` batched steps on its own
        compiled ladder — one catch-up chunk (the committed tokens it
        hasn't ingested, <= 2 lanes, ending with the pending token)
        that yields proposal d_1, then k-1 singles — and the target
        verifies all k+1 positions in ONE all-lane chunked call.
        Acceptance is the deterministic walk: lane j's target choice
        (per-(seed, position)) either equals proposal d_{j+1} (accept,
        continue) or replaces it (the bonus/correction token, stop).
        Returns {id(slot): (committed tokens, k_eff, accepted)} for the
        answer phase; nothing here touches request/slot state."""
        _faults.fire("serving.decode.spec")
        s_bucket = _bucket_for(self._slot_ladder, len(slots))
        keff = [self._k_eff(s) for s in slots]
        for s, ke in zip(slots, keff):
            # the verify chunk writes positions pos .. pos+ke
            self._check_reservation(s, s.pos + ke + 1)
        tables = self.cache.table_array(
            [s.req.seq_id for s in slots], w_bucket, rows=s_bucket)
        proposals: List[List[int]] = [[] for _ in slots]
        with _tracing.span("serving.decode.spec.draft", model=self.name,
                           version=self.version, slots=s_bucket,
                           k=self._spec_k):
            # catch-up + first proposal: feed each slot the committed
            # tokens its draft pool lacks (positions dpos..pos — the
            # last is the pending token), newest-lane logits -> d_1
            gaps = [s.pos - s.dpos for s in slots]
            c1 = _bucket_for(self._draft_chunk_ladder,
                             max(g + 1 for g in gaps))
            tokens = np.zeros((s_bucket, c1), np.int32)
            positions = np.zeros((s_bucket, c1), np.int32)
            q_lens = np.zeros(s_bucket, np.int32)
            lens = np.zeros(s_bucket, np.int32)
            for i, s in enumerate(slots):
                if keff[i] < 1:
                    continue  # bonus-only slot: no proposals needed
                g = gaps[i] + 1
                for j in range(g):
                    tokens[i, j] = s.token_at(s.dpos + j)
                    positions[i, j] = s.dpos + j
                q_lens[i] = g
                lens[i] = s.dpos + g        # == s.pos + 1
            if int(q_lens.max(initial=0)) > 0:
                lg = np.asarray(self._run_draft_arrays(
                    tokens, positions, q_lens, tables, lens))
                for i, s in enumerate(slots):
                    if keff[i] >= 1:
                        proposals[i].append(self._choose(
                            lg[i], s.req, s.pos + 1))
                # singles: feed d_{j-1}, propose d_j
                for j in range(2, self._spec_k + 1):
                    if not any(ke >= j for ke in keff):
                        break
                    tokens = np.zeros((s_bucket, 1), np.int32)
                    positions = np.zeros((s_bucket, 1), np.int32)
                    q_lens = np.zeros(s_bucket, np.int32)
                    lens = np.zeros(s_bucket, np.int32)
                    for i, s in enumerate(slots):
                        if keff[i] >= j:
                            tokens[i, 0] = proposals[i][j - 2]
                            positions[i, 0] = s.pos + j - 1
                            q_lens[i] = 1
                            lens[i] = s.pos + j
                    lg = np.asarray(self._run_draft_arrays(
                        tokens, positions, q_lens, tables, lens))
                    for i, s in enumerate(slots):
                        if keff[i] >= j:
                            proposals[i].append(self._choose(
                                lg[i], s.req, s.pos + j))
        # verify: ONE target call over [pending, d_1..d_k] at the
        # FIXED spec_k+1 chunk entry; lane j's logits are the target's
        # distribution for position pos+1+j
        with _tracing.span("serving.decode.spec.verify",
                           model=self.name, version=self.version,
                           slots=s_bucket, lanes=self._verify_lanes):
            C = self._verify_lanes
            tokens = np.zeros((s_bucket, C), np.int32)
            positions = np.zeros((s_bucket, C), np.int32)
            q_lens = np.zeros(s_bucket, np.int32)
            lens = np.zeros(s_bucket, np.int32)
            for i, s in enumerate(slots):
                tokens[i, 0] = s.token_at(s.pos)
                positions[i, 0] = s.pos
                for j, d in enumerate(proposals[i]):
                    tokens[i, 1 + j] = d
                    positions[i, 1 + j] = s.pos + 1 + j
                q_lens[i] = 1 + keff[i]
                lens[i] = s.pos + 1 + keff[i]
            lg = np.asarray(self._run_verify_arrays(
                tokens, positions, q_lens, tables, lens))  # [B, C, V]
        out: Dict[int, Tuple[List[int], int, int]] = {}
        for i, s in enumerate(slots):
            committed: List[int] = []
            accepted = 0
            for j in range(keff[i] + 1):
                choice = self._choose(lg[i, j], s.req, s.pos + 1 + j)
                committed.append(choice)
                if j < keff[i] and proposals[i][j] == choice:
                    accepted += 1      # d_{j+1} accepted — keep going
                else:
                    break              # bonus/correction token: stop
            out[id(s)] = (committed, keff[i], accepted)
        return out

    def _step(self, live: List[_Slot]):
        # named chaos seam for the SCHEDULER cadence: a
        # `delay@serving.decode.step:*=0.004` plan simulates a slow
        # decoder (long-context model, contended chip) so streaming/
        # failover tests can pin mid-generation behavior without racing
        # a fast engine; `error@` fails the step's slots like any other
        # step failure. Zero cost with no plan installed.
        _faults.fire("serving.decode.step")
        # restore-before-step, COW copies, demand-mode growth (may
        # preempt/demote — the returned live list is authoritative)
        live, grants = self._prepare(live)
        if not live:
            return
        # split the round: decoding slots with a draft attached ride
        # the propose/verify path; prefill chunks (and everything when
        # speculation is off) ride the PR 9 chunked step unchanged
        spec_rows = [i for i, s in enumerate(live)
                     if self._spec_k and not s.req.ev.is_set()
                     and s.pos >= len(s.req.prompt)
                     and s.req.mask is None]
        spec_set = set(spec_rows)
        plain_rows = [i for i in range(len(live)) if i not in spec_set]
        w_need = max(s.pages_held for s in live)
        w_bucket = _bucket_for(self._width_ladder, w_need)
        prefill_toks = sum(grants[i] for i in plain_rows
                           if live[i].pos < len(live[i].req.prompt))
        t0 = time.perf_counter()
        logits_np = sampled = None
        plain_row_of: Dict[int, int] = {}
        spec_out: Dict[int, Tuple[List[int], int, int]] = {}
        # one decode step joins the OLDEST live request's trace (a span
        # has one parent); per-slot request spans live in the server
        with _tracing.adopt(live[0].req.trace_ctx), \
                _tracing.span("serving.decode.step", model=self.name,
                              version=self.version, width=w_bucket,
                              prefill_tokens=prefill_toks,
                              spec_slots=len(spec_rows),
                              live=len(live)):
            if plain_rows:
                ps_slots = [live[i] for i in plain_rows]
                ps_grants = [grants[i] for i in plain_rows]
                s_bucket = _bucket_for(self._slot_ladder, len(ps_slots))
                # pure-decode steps (and 1-token prefill tails) ride
                # the C=1 shapes — exactly the PR 6 step; only steps
                # carrying a real chunk pay the chunk-wide compute
                c_bucket = _bucket_for(self._chunk_ladder,
                                       max(max(ps_grants), 1))
                tokens = np.zeros((s_bucket, c_bucket), np.int32)
                positions = np.zeros((s_bucket, c_bucket), np.int32)
                q_lens = np.zeros(s_bucket, np.int32)
                lens = np.zeros(s_bucket, np.int32)
                for i, (s, g) in enumerate(zip(ps_slots, ps_grants)):
                    plain_row_of[id(s)] = i
                    for j in range(g):
                        tokens[i, j] = s.token_at(s.pos + j)
                        positions[i, j] = s.pos + j
                    q_lens[i] = g
                    # keys INCLUDING this chunk; within it, query j
                    # attends only keys up to its own position
                    lens[i] = s.pos + g
                    self._check_reservation(s, int(lens[i]))
                tables = self.cache.table_array(
                    [s.req.seq_id for s in ps_slots], w_bucket,
                    rows=s_bucket)
                logits = self._run_step_arrays(tokens, positions,
                                               q_lens, tables, lens)
                if self._spec_k:
                    # the draft shadows every prefill chunk so its
                    # mirrored pool tracks the committed sequence
                    # (logits discarded; its watermark advances in the
                    # answer phase with pos)
                    self._run_draft_arrays(tokens, positions, q_lens,
                                           tables, lens)
                logits_np = np.asarray(logits)  # [B, vocab] — newest
                # the greedy fast path for the whole batch; per-request
                # sampling policies resolve per slot below
                sampled = np.asarray(np.argmax(logits_np, axis=-1))
            if spec_rows:
                spec_out = self._spec_substep(
                    [live[i] for i in spec_rows], w_bucket)
        _m_step_ms.observe((time.perf_counter() - t0) * 1e3)
        _m_steps.inc()
        _m_occupancy.observe(
            len(live) / float(_bucket_for(self._slot_ladder,
                                          len(live))))
        # prices the token-budget policy next to occupancy: how much of
        # each step's budget real prefill work consumed
        _m_prefill_per_step.observe(prefill_toks)
        if prefill_toks:
            _m_prefill_tokens.inc(prefill_toks)
        with self._cond:
            self._n_steps += 1
        now = time.monotonic()
        done: List[_Slot] = []
        # the whole answer phase holds _cond: stop(drain=False) fails
        # requests under _cond, so check-ev-then-answer must be atomic
        # with it or the two sides can each answer the same request
        notes: Dict[int, int] = {}
        produced_any = False
        n_proposed = n_accepted = 0
        with self._cond:
            for i, s in enumerate(live):
                if s.req.ev.is_set():
                    # already answered — stop(drain=False) raced this
                    # step and failed the request; don't double-answer
                    # or count a completion/token for it
                    done.append(s)
                    continue
                s.steps += 1
                finished = False
                if id(s) in spec_out:
                    committed, ke, acc = spec_out[id(s)]
                    pos_old = s.pos
                    s.req.spec_proposed += ke
                    s.req.spec_accepted += acc
                    n_proposed += ke
                    n_accepted += acc
                    for tok in committed:
                        s.pos += 1
                        s.req.produced.append(tok)
                        produced_any = True
                        _m_tokens.inc()
                        if s.first_token_steps is None:
                            s.first_token_steps = s.steps
                            _m_first_token_steps.observe(s.steps)
                        if (len(s.req.produced) >= s.req.max_new
                                or (self.spec.eos_id is not None
                                    and tok == self.spec.eos_id)):
                            # tokens past an accepted eos are
                            # discarded: the committed walk ends here
                            finished = True
                            break
                    if ke > 0 and not finished:
                        # draft validity watermark: the draft wrote
                        # through pos_old+ke-1 and tokens are committed
                        # through pos_old+acc — a fully-accepted round
                        # leaves it one token behind (it never fed its
                        # own last proposal), anything else re-syncs
                        s.dpos = pos_old + min(ke - 1, acc) + 1
                    if not finished and self._reservation == "demand":
                        # ROLLBACK (ISSUE 14): any page grown for this
                        # verify chunk that now holds ONLY rejected
                        # positions goes straight back to the pool;
                        # coverage for the pending token's next write
                        # (pos itself) is kept so acceptance never
                        # thrashes grow/shrink. note_tokens_many below
                        # records the rolled-back pos — the "un-note".
                        need = self.cache.allocator.pages_for_tokens(
                            s.pos + 1)
                        if s.pages_held > need:
                            s.pages_held -= self.cache.allocator.shrink(
                                s.req.seq_id, s.pages_held - need)
                else:
                    g = grants[i]    # >= 1: every live slot progresses
                    s.pos += g
                    if self._prefix_on and not s.req.published and \
                            s.pos >= len(s.req.prompt):
                        # prompt K/V fully on-device as of THIS step:
                        # publish the prompt pages into the prefix
                        # index (metadata only; from here they are
                        # immutable — this sequence only ever writes
                        # PAST them, and they outlive its free() as
                        # the shared cache)
                        self.cache.allocator.publish(s.req.seq_id,
                                                     s.req.prompt)
                        s.req.published = True
                    if self._spec_k:
                        # the draft shadowed this prefill chunk lane
                        # for lane — its watermark advances in lockstep
                        s.dpos = s.pos
                    tok = None
                    mask_done = False
                    if s.pos >= len(s.req.prompt):
                        # logits_np[row] is the slot's newest lane (the
                        # step unembeds only lane q_len-1): prompt
                        # token P-1 when the chunk just finished
                        # prefill, else the decode token. s.pos is the
                        # new token's absolute index in its sequence —
                        # the (seed, position) pair that makes sampling
                        # independent of batch composition AND chunking
                        row = plain_row_of[id(s)]
                        if s.req.want_topk and s.req.first_topk is None:
                            # the beam fork point (ISSUE 20): the FIRST
                            # generated position's token order by
                            # logit, stable-sorted so ties break
                            # deterministically; order[0] == argmax, so
                            # beam 0 is the greedy continuation
                            order = np.argsort(
                                -np.asarray(logits_np[row], np.float64),
                                kind="stable")
                            s.req.first_topk = [
                                int(t) for t in order[:s.req.want_topk]]
                        if s.req.mask is not None:
                            tok, mask_done = self._masked_choice(
                                s.req, logits_np[row], s.pos)
                        else:
                            tok = (int(sampled[row])
                                   if s.req.temperature <= 0.0
                                   else sample_token(
                                       logits_np[row],
                                       s.req.temperature,
                                       s.req.top_k, s.req.seed, s.pos))
                        s.req.produced.append(tok)
                        produced_any = True
                        _m_tokens.inc()
                        if s.first_token_steps is None:
                            s.first_token_steps = s.steps
                            _m_first_token_steps.observe(s.steps)
                    finished = (len(s.req.produced) >= s.req.max_new
                                or mask_done
                                or (tok is not None
                                    and self.spec.eos_id is not None
                                    and tok == self.spec.eos_id))
                notes[s.req.seq_id] = s.pos
                if finished:
                    # finished beats a lapsed deadline: the result is
                    # fully paid for — deliver it rather than discard
                    done.append(s)
                    self._complete(s)
                elif s.req.deadline is not None and now > s.req.deadline:
                    _m_deadline_miss.inc()
                    done.append(s)
                    self._fail_locked(s.req, DeadlineExceeded(
                        f"request to decoder '{self.name}' lapsed "
                        f"mid-decode after {len(s.req.produced)} tokens"))
            # one allocator-lock round-trip for the whole step; seqs
            # freed by _complete/_fail above are skipped inside
            self.cache.allocator.note_tokens_many(notes)
            if done:
                self._slots = [s for s in self._slots if s not in done]
                self._g_live.set(len(self._slots))
            if done or produced_any:
                # wake completion waiters AND streaming readers parked
                # in stream_tokens — a token exists the moment this
                # notify lands, ceil(prompt/chunk) steps after
                # admission, not when the whole sequence finishes
                self._cond.notify_all()
        if n_proposed:
            _m_spec_proposed.inc(n_proposed)
            _m_spec_accepted.inc(n_accepted)
            _m_spec_rejected.inc(n_proposed - n_accepted)

    def _complete(self, s: _Slot):
        self.cache.allocator.free(s.req.seq_id)
        _m_completions.inc()
        _m_total.observe((time.monotonic() - s.req.t_enq) * 1e3)
        s.req.result = {
            "tokens": list(s.req.produced),
            "prompt_len": int(len(s.req.prompt)),
            "version": self.version,
            # scheduler steps from admission to the first generated
            # token — the load-independent chunked-prefill evidence
            # (ceil(P/chunk) + co-riding, vs P unchunked; for a
            # prefix-cache hit, suffix takes the prompt's place:
            # ceil((P - cached)/chunk))
            "steps_to_first_token": int(s.first_token_steps or s.steps),
            # prompt tokens answered from the prefix index instead of
            # prefilled (0 = cold)
            "cached_tokens": int(s.req.cached_tokens),
            # speculative decoding (ISSUE 14): draft proposals this
            # request saw and the fraction the target accepted (None =
            # no speculative round touched it / speculation off)
            "spec_proposed": int(s.req.spec_proposed),
            "spec_accepted": int(s.req.spec_accepted),
            "accept_rate": (
                round(s.req.spec_accepted / s.req.spec_proposed, 4)
                if s.req.spec_proposed else None),
        }
        if s.req.want_topk:
            # the beam fork point rides the ordinary result dict —
            # absent unless asked for, so every pre-existing result
            # shape is untouched
            s.req.result["first_topk"] = list(s.req.first_topk or [])
        if s.req.spec_proposed:
            _m_spec_accept_rate.observe(
                s.req.spec_accepted / s.req.spec_proposed)
        s.req.ev.set()

    # -- the embed lane ---------------------------------------------------
    def _embed_step(self, live: List[_EmbedSlot]):
        """One chunked-prefill step for the embedding/scoring lane
        (ISSUE 20): the same Sarathi-style token budget, page tables,
        and compiled ladders as generation — but the all-lane + hidden
        step form, and nothing is ever sampled: every lane feeds the
        pooled-hidden accumulator and the per-token logprobs. Decode
        slots are untouched by construction (separate slot list)."""
        # named chaos seam for the embed cadence (mirrors
        # serving.decode.step); the workload layer's per-kind site
        # (serving.workload.embed) lives at the dispatch boundary
        _faults.fire("serving.decode.embed")
        budget = self._prefill_chunk
        grants = []
        for s in live:
            remaining = len(s.req.prompt) - s.pos
            g = max(1, min(remaining, budget))
            budget = max(0, budget - g)
            grants.append(g)
        s_bucket = _bucket_for(self._slot_ladder, len(live))
        c_bucket = _bucket_for(self._chunk_ladder, max(grants))
        w_need = max(s.pages_held for s in live)
        w_bucket = _bucket_for(self._width_ladder, w_need)
        tokens = np.zeros((s_bucket, c_bucket), np.int32)
        positions = np.zeros((s_bucket, c_bucket), np.int32)
        q_lens = np.zeros(s_bucket, np.int32)
        lens = np.zeros(s_bucket, np.int32)
        with self._cond:
            for i, (s, g) in enumerate(zip(live, grants)):
                if s.req.ev.is_set():
                    continue  # canceled: pages freed, all-garbage row
                for j in range(g):
                    tokens[i, j] = int(s.req.prompt[s.pos + j])
                    positions[i, j] = s.pos + j
                q_lens[i] = g
                lens[i] = s.pos + g
                if int(lens[i]) > s.pages_held * self.cache.page_size:
                    raise ServingError(
                        f"embed chunk grant escaped seq "
                        f"{s.req.seq_id}'s page reservation")
        tables = self.cache.table_array(
            [s.req.seq_id for s in live], w_bucket, rows=s_bucket)
        t0 = time.perf_counter()
        with _tracing.adopt(live[0].req.trace_ctx), \
                _tracing.span("serving.decode.embed", model=self.name,
                              version=self.version, width=w_bucket,
                              live=len(live)):
            logits, hidden = self._run_embed_arrays(
                tokens, positions, q_lens, tables, lens)
        logits_np = np.asarray(logits)  # [B, C, vocab]
        hidden_np = np.asarray(hidden)  # [B, C, d_model]
        _m_step_ms.observe((time.perf_counter() - t0) * 1e3)
        now = time.monotonic()
        done: List[_EmbedSlot] = []
        notes: Dict[int, int] = {}
        with self._cond:
            self._n_steps += 1
            for i, s in enumerate(live):
                if s.req.ev.is_set():
                    done.append(s)
                    continue
                s.steps += 1
                g = grants[i]
                prompt = s.req.prompt
                s.req.hidden_sum += np.asarray(
                    hidden_np[i, :g], np.float64).sum(axis=0)
                lg = np.asarray(logits_np[i, :g], np.float64)
                # float64 log-softmax per lane; lane j (absolute
                # position pos+j) scores the NEXT prompt token — the
                # final lane has no successor inside the prompt
                mx = lg.max(axis=-1)
                lse = mx + np.log(
                    np.exp(lg - mx[:, None]).sum(axis=-1))
                for j in range(g):
                    nxt = s.pos + j + 1
                    if nxt < len(prompt):
                        s.req.logprobs.append(
                            float(lg[j, int(prompt[nxt])] - lse[j]))
                s.pos += g
                _m_embed_tokens.inc(g)
                notes[s.req.seq_id] = s.pos
                if s.pos >= len(prompt):
                    done.append(s)
                    self._complete_embed(s)
                elif s.req.deadline is not None and now > s.req.deadline:
                    _m_deadline_miss.inc()
                    done.append(s)
                    self._fail_locked(s.req, DeadlineExceeded(
                        f"embed request to decoder '{self.name}' "
                        f"lapsed mid-prefill at {s.pos} tokens"))
            self.cache.allocator.note_tokens_many(notes)
            if done:
                self._embed_slots = [s for s in self._embed_slots
                                     if s not in done]
                self._g_embed.set(len(self._embed_slots))
                self._cond.notify_all()

    def _complete_embed(self, s: _EmbedSlot):
        self.cache.allocator.free(s.req.seq_id)
        _m_completions.inc()
        _m_total.observe((time.monotonic() - s.req.t_enq) * 1e3)
        p = len(s.req.prompt)
        s.req.result = {
            "embedding": [float(x) for x in s.req.hidden_sum / p],
            "logprobs": list(s.req.logprobs),
            "prompt_len": p,
            "version": self.version,
            "steps": int(s.steps),
        }
        s.req.ev.set()
