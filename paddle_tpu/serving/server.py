"""ServingServer — the RPC front door of the serving subsystem.

Rides distributed/rpc.py (the same length-prefixed JSON + raw-segment
framing the pserver uses), so serving inherits the whole PR 1-4
infrastructure for free: idempotency-token dedup, retry-safe clients,
per-method latency histograms, trace-context adoption, and named fault
sites for chaos plans.

Methods (all fire the `serving.<method>` fault site before running, so
`PADDLE_TPU_FAULTS='error@serving.infer:0'` chaos plans reach them):

    infer(model, feeds, deadline_ms)   -> {model, version, outputs}
    load_report()                      -> structured per-model load:
                                          free KV pages / live slots
                                          (decoders), queue depths,
                                          model/version set — the
                                          signal a FleetRouter balances
                                          on (paddle_tpu/fleet)
    generate(model, prompt, max_new_tokens, deadline_ms)
                                       -> {model, version, tokens,
                                           prompt_len}  (decoders)
    generate_stream_start(model, prompt, ...)
                                       -> {stream, version, prompt_len}
    generate_stream_next(stream, offset, wait_ms)
                                       -> {tokens, next_offset, done,
                                           result?}  — the pull half of
                                          STREAMING generate (ISSUE 12):
                                          tokens cross the wire as they
                                          are decoded, the first one
                                          ~ceil(prompt/chunk) steps
                                          after admission
    generate_stream_close(stream)      -> cancels an unfinished stream
    load_model(model, dirname, ...)    -> engine stats (after warmup)
    load_decoder(model, spec, ...,
                 checkpoint_dir=)      -> decode-engine stats (after the
                                          full slot/width warm);
                                          checkpoint_dir deploys REAL
                                          weights from a verified
                                          manifest checkpoint
                                          (paddle_tpu/checkpoint)
    unload_model(model)                -> final engine stats
    list_models()                      -> {name: stats}
    health()                           -> {"ok": True, "models": [...]}

Retry semantics: `infer` is SEMANTICALLY idempotent (pure function of
its feeds), but it is deliberately NOT declared in RpcServer's
`idempotent` set — it rides the dedup cache instead, so a client
retransmit after a lost reply is answered from the cached response
without re-running the batch (rpc.server.dedup_hits counts exactly one
per retransmitted frame; the chaos test pins this). `generate` rides
the dedup cache for the stronger reason: re-decoding a whole sequence
on a retransmit would burn len(prompt)+max_new decode steps AND
re-reserve KV pages — the chaos test pins that a killed generate reply
is answered from the cache with zero extra decode steps. Re-execution would
be CORRECT but wasteful — and under overload, wasteful is wrong.
The three stream methods ride the dedup cache for the same reasons: a
retransmitted `generate_stream_start` must not admit (and reserve
pages for) a SECOND sequence, and a retransmitted continuation frame
is answered token-exact with zero extra decode steps — each frame is a
pure read of (stream state, client-owned offset), so exactness is
pinned PER TOKEN, not per request (the partial-stream chaos test pins
`rpc.server.dedup_hits` == injected reply drops). Sizing note for
heavy streaming: every frame response occupies a dedup slot for >=
900s, so budget `dedup_cap` for the fleet's aggregate frame rate
(streams x frames/stream) — past the cache's 4x-cap safety valve the
OLDEST completed entries evict early, and a start/generate whose entry
was valved out re-executes on retransmit (for a frame that is harmless
— pure read, token-exact — for a start it admits a duplicate sequence
that idles until the stream TTL reaps it; raise `dedup_cap` before a
fleet gets there).
Memory sizing note: the dedup cache holds recent infer RESPONSES (up
to `dedup_cap`, held >= 900s, 4x-cap safety valve — see
rpc._DedupCache); budget `dedup_cap x typical response bytes` of
serving-host RAM, and shrink `dedup_cap` for models with large
outputs. `health`/`list_models`/`load_report` are declared idempotent:
cheap reads whose responses must not occupy dedup-cache slots —
`load_report` especially, because a router scrapes it on the ROUTING
path (once per scrape-TTL window per replica) and a load snapshot
pinned in the dedup cache would be both stale and wasted memory. Overload/deadline/
not-found rejections are application errors — RpcClient never retries
them, so a shedding server is not hammered by its own rejects.

Admission control happens in the ENGINE (bounded queue depth →
immediate structured ServerOverloaded): by the time a request would
have to wait unboundedly, it has already been refused.

A hot-swap retires the old engine only after the registry pointer
flipped; a request that raced the flip gets EngineRetired from the old
engine and is transparently resubmitted to the current one
(`serving.swap_resubmits`) — zero requests fail because a deploy
happened.
"""
from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..distributed import faults as _faults
from ..distributed.rpc import RpcServer
from ..observability import debug_server as _debug, metrics as _metrics, \
    tracing as _tracing
from ..observability.log import get_logger
from .engine import InferenceEngine
from .errors import (EngineRetired, ModelNotFound, ServerOverloaded,
                     ServingError, StreamExpired)
from .registry import ModelRegistry

__all__ = ["ServingServer"]

_log = get_logger("serving")

_m_resubmits = _metrics.counter("serving.swap_resubmits")
# streaming generate (ISSUE 12): starts/chunks/tokens count what
# actually crossed the wire incrementally; expired counts abandoned
# streams the idle sweep canceled (their KV pages freed)
_m_stream_starts = _metrics.counter("serving.stream.starts")
_m_stream_chunks = _metrics.counter("serving.stream.chunks")
_m_stream_tokens = _metrics.counter("serving.stream.tokens")
_m_stream_expired = _metrics.counter("serving.stream.expired")


class ServingServer:
    """RPC serving front end over a ModelRegistry."""

    # a request may race at most this many consecutive retirements (each
    # get() after a retirement returns the freshly-flipped engine, so >1
    # loop only happens under back-to-back deploys)
    _SWAP_RETRIES = 8

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 dedup_cap: int = 1024, max_streams: int = 256,
                 stream_ttl: Optional[float] = None):
        from ..fluid.flags import FLAGS

        self._registry = registry or ModelRegistry()
        # open token streams (ISSUE 12): stream id -> {req, engine,
        # model, touched}. Bounded (max_streams) and idle-swept: a
        # stream nobody polls for stream_ttl seconds is canceled so an
        # abandoned client can't pin KV pages forever
        self._streams_mu = threading.Lock()
        self._streams: Dict[str, Dict[str, Any]] = {}  # guarded-by: _streams_mu
        self._max_streams = int(max_streams)
        self._stream_ttl = float(FLAGS["serving_stream_ttl"]
                                 if stream_ttl is None else stream_ttl)
        self._last_sweep = 0.0  # guarded-by: _streams_mu
        handlers = {
            "infer": self._infer,
            "generate": self._generate,
            "workload": self._workload,
            "generate_stream_start": self._generate_stream_start,
            "generate_stream_next": self._generate_stream_next,
            "generate_stream_close": self._generate_stream_close,
            "load_model": self._load_model,
            "load_decoder": self._load_decoder,
            "unload_model": self._unload_model,
            "list_models": self._list_models,
            "load_report": self._load_report,
            "health": self._health,
        }
        self._rpc = RpcServer(
            {m: self._guarded(m, fn) for m, fn in handlers.items()},
            dedup_cap=dedup_cap,
            idempotent={"health", "list_models", "load_report"},
        )
        # serializes load_model end-to-end: auto-versioning is a
        # read-then-deploy sequence, and two concurrent deploys of one
        # model racing it would mint duplicate version numbers (deploys
        # are rare and already compile-bound — serializing them costs
        # nothing that matters)
        self._load_mu = threading.Lock()

    @staticmethod
    def _guarded(method: str, fn):
        """Every handler fires its `serving.<method>` fault site first,
        so chaos plans (`error@serving.infer:0`) reach the serving layer
        by name — the same seam the RPC transport already has."""
        def handler(*args, **kw):
            _faults.fire(f"serving.{method}")
            return fn(*args, **kw)
        return handler

    @property
    def registry(self) -> ModelRegistry:
        return self._registry

    # -- lifecycle --------------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0
              ) -> Tuple[str, int]:
        addr = self._rpc.serve(host, port)
        _tracing.set_process_label(f"serving:{addr[1]}")
        _log.info("serving server listening on %s:%d", *addr)
        # live introspection: PADDLE_TPU_DEBUG_PORT attaches the shared
        # debug server; /statusz grows a "serving:<port>" section
        # (models, versions, bucket ladders, queue depths, transport).
        # Per-INSTANCE name: two servers in one process must not clobber
        # each other's section (or deregister the survivor's on shutdown)
        _debug.maybe_serve_from_env()
        self._status_name = f"serving:{addr[1]}"
        _debug.add_status(self._status_name, self._status)
        return addr

    @property
    def address(self) -> Tuple[str, int]:
        return self._rpc.address

    def shutdown(self, drain: bool = True):
        _debug.remove_status(getattr(self, "_status_name", None))
        self._rpc.shutdown()
        self._registry.unload_all(drain=drain)

    def kill(self):
        """Chaos seam: die the way a SIGKILLed replica dies — the
        transport severs every established connection mid-whatever
        (peers see resets, lost replies, refused dials), and NOTHING is
        drained or unloaded: engines keep whatever they were doing,
        answers go nowhere. The fleet chaos tests kill replicas with
        this; a FleetRouter must fail the traffic over."""
        _debug.remove_status(getattr(self, "_status_name", None))
        self._rpc.kill()

    def _status(self) -> Dict[str, Any]:
        return {"models": self._registry.stats(),
                "rpc": self._rpc.stats()}

    # -- handlers ---------------------------------------------------------
    def _on_engine(self, model: str, want_decoder: bool, mismatch: str,
                   fn):
        """THE swap-resubmit contract, in one place for infer/generate/
        stream-start: a request that races a hot-swap gets EngineRetired
        from the old engine — the registry already points at the
        replacement, so resubmit there, never fail the request."""
        model = str(model)
        for _ in range(self._SWAP_RETRIES):
            engine = self._registry.get(model)
            if (engine.kind == "decoder") != want_decoder:
                raise ServingError(mismatch.format(model=model))
            try:
                return fn(engine)
            except EngineRetired:
                _m_resubmits.inc()
                continue
        raise ServingError(
            f"model '{model}' kept retiring across "
            f"{self._SWAP_RETRIES} resubmits — deploy storm?")

    def _infer(self, model: str, feeds: Dict[str, Any],
               deadline_ms: Optional[float] = None) -> Dict[str, Any]:
        with _tracing.span("serving.request", model=str(model)):
            def run(engine):
                outputs, version = engine.infer(
                    feeds, deadline_ms=deadline_ms)
                return {"model": str(model), "version": version,
                        "outputs": [np.asarray(o) for o in outputs]}

            return self._on_engine(
                model, False,
                "model '{model}' is a decoder — call generate, "
                "not infer", run)

    def _generate(self, model: str, prompt: Sequence[int],
                  max_new_tokens: int = 16,
                  deadline_ms: Optional[float] = None,
                  temperature: float = 0.0, top_k: int = 0,
                  seed: int = 0) -> Dict[str, Any]:
        """Autoregressive decode on a loaded DecodeEngine. Same swap-
        resubmit contract as _infer: racing a hot-swap re-enqueues on
        the replacement decoder instead of failing the request.
        Sampling params thread through per request (decode.sample_token;
        deterministic given seed, so the dedup cache's answer to a
        retransmit equals what a re-decode would have produced)."""
        with _tracing.span("serving.decode.request", model=str(model)):
            return self._on_engine(
                model, True,
                "model '{model}' is not a decoder — call infer, "
                "not generate",
                lambda engine: {
                    "model": str(model),
                    **engine.generate(
                        prompt, max_new_tokens=max_new_tokens,
                        deadline_ms=deadline_ms, temperature=temperature,
                        top_k=top_k, seed=seed)})

    def _workload(self, model: str, workload: Dict[str, Any]
                  ) -> Dict[str, Any]:
        """Typed-workload dispatch (ISSUE 20): one RPC, one ``kind``
        field selecting generate/constrained/embed/beam. Parse STRICTLY
        before touching any engine (an unknown kind or misspelled field
        must refuse, not silently decode unconstrained), then run under
        the same swap-resubmit contract as _generate. Deliberately NOT
        in the transport's idempotent set: a retransmit after a lost
        reply must be answered from the dedup cache
        (rpc.server.dedup_hits), not recomputed — beams and embeddings
        are exactly the requests expensive enough to make recompute-on-
        retry a real cost."""
        from .workloads import parse_workload, run_workload

        w = parse_workload(workload)
        return self._on_engine(
            model, True,
            "model '{model}' is not a decoder — workloads need a "
            "DecodeEngine",
            lambda engine: {"model": str(model),
                            **run_workload(engine, w)})

    # -- streaming generate (ISSUE 12) ------------------------------------
    def _sweep_streams(self):
        """Cancel + drop streams nobody polled for stream_ttl seconds.
        Collect under the lock, cancel outside it (cancel takes the
        ENGINE's condition — never nest it under _streams_mu). TIME-
        GATED: every stream method calls this, and under heavy frame
        traffic a full-table scan per frame would turn _streams_mu
        into a data-path serialization point — the TTL is a seconds-
        scale promise, so one scan per ~ttl/10 keeps it at an O(1)
        check per frame."""
        now = time.monotonic()
        expired: List[Tuple[Any, Any]] = []
        with self._streams_mu:
            gate = min(30.0, max(0.05, self._stream_ttl / 10.0))
            if now - self._last_sweep < gate:
                return
            self._last_sweep = now
            for sid in list(self._streams):
                ent = self._streams[sid]
                if now - ent["touched"] > self._stream_ttl:
                    expired.append(self._streams.pop(sid))
        for ent in expired:
            _m_stream_expired.inc()
            _log.warning("stream on '%s' idle past %.0fs — canceling "
                         "the abandoned sequence", ent["model"],
                         self._stream_ttl)
            try:
                ent["engine"].cancel(ent["req"], msg="stream abandoned")
            except Exception:  # pragma: no cover - engine mid-retire
                pass

    def _generate_stream_start(self, model: str, prompt: Sequence[int],
                               max_new_tokens: int = 16,
                               deadline_ms: Optional[float] = None,
                               temperature: float = 0.0, top_k: int = 0,
                               seed: int = 0) -> Dict[str, Any]:
        """Admit a decode sequence and hand back a stream id; tokens
        are pulled incrementally with generate_stream_next. Rides the
        dedup cache (NOT idempotent-declared): a retransmitted start
        after a lost reply is answered with the ORIGINAL stream id —
        one admission, one page reservation, no duplicate sequence."""
        self._sweep_streams()
        with _tracing.span("serving.stream.start", model=str(model)):
            def run(engine):
                req = engine.submit(
                    prompt, max_new_tokens=max_new_tokens,
                    deadline_ms=deadline_ms, temperature=temperature,
                    top_k=top_k, seed=seed)
                sid = uuid.uuid4().hex
                # bound checked at INSERT (one locked section, no
                # check-then-act window for concurrent starts to
                # overshoot through); the submit is withdrawn on refusal
                with self._streams_mu:
                    full = len(self._streams) >= self._max_streams
                    if not full:
                        self._streams[sid] = {
                            "req": req, "engine": engine,
                            "model": str(model),
                            "touched": time.monotonic()}
                if full:
                    engine.cancel(req, msg="stream table full")
                    raise ServerOverloaded(
                        f"too many open token streams "
                        f"({self._max_streams}) — close or drain some "
                        "first")
                _m_stream_starts.inc()
                return {"stream": sid, "model": str(model),
                        "version": engine.version,
                        "prompt_len": len(req.prompt)}

            return self._on_engine(
                model, True,
                "model '{model}' is not a decoder — streaming "
                "generate needs one", run)

    def _generate_stream_next(self, stream: str, offset: int,
                              wait_ms: float = 20000.0
                              ) -> Dict[str, Any]:
        """One continuation frame: every token past ``offset``, blocking
        (bounded) until at least one exists or the sequence ends. A pure
        read of the stream's request state — the client owns the cursor
        — so a retransmitted frame (dedup-answered OR re-executed) is
        token-exact with zero extra decode steps. A failed sequence
        re-raises its typed error."""
        # every stream method sweeps: the TTL promise must not depend
        # on another START ever arriving (steady frame-only traffic
        # would otherwise pin abandoned entries — and their retired
        # engines' KV pools — forever)
        self._sweep_streams()
        with self._streams_mu:
            ent = self._streams.get(str(stream))
            if ent is not None:
                ent["touched"] = time.monotonic()
        if ent is None:
            raise StreamExpired(
                f"unknown stream '{stream}' — closed, expired "
                f"(idle > {self._stream_ttl:.0f}s), or from a previous "
                "server life")
        out = ent["engine"].stream_tokens(
            ent["req"], offset, timeout=max(0.0, float(wait_ms)) / 1e3)
        _m_stream_chunks.inc()
        if out["tokens"]:
            _m_stream_tokens.inc(len(out["tokens"]))
        return out

    def _generate_stream_close(self, stream: str) -> Dict[str, Any]:
        """Drop the stream; an unfinished sequence is canceled (pages
        freed now, the scheduler drops its slot at the next answer
        phase). Rides the dedup cache like start, so a retransmitted
        close cannot cancel a stream id a later caller was handed."""
        self._sweep_streams()
        with self._streams_mu:
            ent = self._streams.pop(str(stream), None)
        canceled = False
        if ent is not None and not ent["req"].ev.is_set():
            try:
                canceled = ent["engine"].cancel(
                    ent["req"], msg="stream closed by client")
            except Exception:  # pragma: no cover - engine mid-retire
                pass
        return {"closed": ent is not None, "canceled": canceled}

    def _resolve_version(self, model: str, version: Optional[int]) -> int:
        """Auto-assign (live+1) or validate a pinned version. A pinned
        version EQUAL to the live one is refused: the new engine would
        mint the same per-version gauge series (queue_depth/live_slots/
        kv pool) and the old engine's retirement would then zero the
        live engine's gauges — the clobber the per-version keying
        exists to prevent. Redeploying an older (or any other) pinned
        version is fine; only the collision is an error."""
        try:
            live = self._registry.get(model).version
        except ModelNotFound:
            live = None
        if version is None:
            return 1 if live is None else live + 1
        version = int(version)
        if live is not None and version == live:
            raise ValueError(
                f"model '{model}' v{version} is already the live "
                f"version — pin a different version or omit it to "
                f"auto-assign v{live + 1}")
        return version

    @staticmethod
    def _resolve_decoder_artifact(what: str, spec, checkpoint_dir):
        """One rule for (spec dict, checkpoint_dir) -> (DecoderSpec,
        params, mesh_meta), shared by the target and the speculative
        draft (ISSUE 14): a checkpoint loads real weights and its saved
        spec, a bare spec builds the deterministic seed decoder, and
        giving both cross-validates — a contradiction is a wrong-model
        deploy, refused before any compile. ``mesh_meta`` is the mesh
        the checkpoint RECORDED at export (ISSUE 15; None for
        single-chip artifacts or bare specs)."""
        from .decode import DecoderSpec

        if checkpoint_dir is not None:
            from ..checkpoint import (decoder_checkpoint_mesh,
                                      load_decoder_checkpoint)

            use_spec, params = load_decoder_checkpoint(
                str(checkpoint_dir))
            mesh_meta = decoder_checkpoint_mesh(str(checkpoint_dir))
            if spec is not None:
                want = DecoderSpec.from_dict(dict(spec))
                if want.to_dict() != use_spec.to_dict():
                    raise ValueError(
                        f"{what} spec given to load_decoder contradicts "
                        f"checkpoint '{checkpoint_dir}': "
                        f"{want.to_dict()} != {use_spec.to_dict()}")
            return use_spec, params, mesh_meta
        if spec is None:
            return None, None, None
        return DecoderSpec.from_dict(dict(spec)), None, None

    def _load_decoder(self, model: str,
                      spec: Optional[Dict[str, Any]] = None,
                      version: Optional[int] = None,
                      slots: Optional[Sequence[int]] = None,
                      page_size: Optional[int] = None,
                      num_pages: Optional[int] = None,
                      max_seq_len: Optional[int] = None,
                      max_queue: Optional[int] = None,
                      prefill_chunk: Optional[int] = None,
                      checkpoint_dir: Optional[str] = None,
                      prefix_cache: Optional[bool] = None,
                      reservation: Optional[str] = None,
                      draft_spec: Optional[Dict[str, Any]] = None,
                      draft_checkpoint_dir: Optional[str] = None,
                      spec_k: Optional[int] = None,
                      mesh_axes: Optional[str] = None,
                      embeddings: bool = False
                      ) -> Dict[str, Any]:
        """Build + warm (every slot/width shape) + atomically install a
        DecodeEngine. ``checkpoint_dir`` loads REAL weights (and the
        spec) from a manifest checkpoint (ISSUE 12 — checksum-verified,
        typed tensor-named failure on corruption); ``spec`` alone
        deploys the deterministic seed-built decoder as before. Giving
        both cross-validates: a spec that contradicts the checkpoint's
        is a wrong-model deploy, refused before any compile.
        ``draft_spec``/``draft_checkpoint_dir`` attach a speculative
        DRAFT decoder the same way (ISSUE 14; cross-validated against
        the target — same vocab/eos required, typed refusal naming the
        field) and ``spec_k`` pins the proposals-per-round (None = the
        server's autotune cache / FLAGS default). Hot-swapping a
        decoder drains the old engine — every in-flight SEQUENCE
        finishes on its own KV cache before the old pool releases."""
        from .decode import DecodeEngine

        model = str(model)
        use_spec, params, ckpt_mesh = self._resolve_decoder_artifact(
            "target", spec, checkpoint_dir)
        if use_spec is None:
            raise ValueError(
                "load_decoder needs a spec dict or a checkpoint_dir")
        use_draft, draft_params, _ = self._resolve_decoder_artifact(
            "draft", draft_spec, draft_checkpoint_dir)
        # mesh resolution (ISSUE 15): explicit mesh_axes wins ('' pins
        # single-chip), else the mesh the checkpoint RECORDED at
        # export, else None = the engine's FLAGS['serving_mesh_axes']
        # default
        mesh_arg: Optional[Any] = None
        mesh_rules_arg: Optional[Any] = None
        if mesh_axes is not None:
            mesh_arg = str(mesh_axes)
        elif ckpt_mesh is not None:
            from ..mesh import MeshSpec

            mesh_arg = MeshSpec.from_dict(ckpt_mesh["spec"])
            mesh_rules_arg = ckpt_mesh.get("rules")
        # lint: allow-blocking — deploys serialize end-to-end; see
        # _load_mu above. generate/infer traffic never takes this lock.
        with self._load_mu:
            version = self._resolve_version(model, version)

            def build():
                return DecodeEngine(
                    use_spec, name=model,
                    version=version, slots=slots, page_size=page_size,
                    num_pages=num_pages, max_seq_len=max_seq_len,
                    max_queue=max_queue, prefill_chunk=prefill_chunk,
                    params=params,
                    prefix_cache=(None if prefix_cache is None
                                  else bool(prefix_cache)),
                    reservation=(None if reservation is None
                                 else str(reservation)),
                    draft_spec=use_draft, draft_params=draft_params,
                    spec_k=(None if spec_k is None else int(spec_k)),
                    mesh=mesh_arg, mesh_rules=mesh_rules_arg,
                    embeddings=bool(embeddings))

            engine = self._registry.deploy(model, build)
            return engine.stats()

    def _load_model(self, model: str, dirname: str,
                    version: Optional[int] = None,
                    kind: str = "auto",
                    buckets: Optional[Sequence[int]] = None,
                    max_queue: Optional[int] = None,
                    max_wait_ms: Optional[float] = None) -> Dict[str, Any]:
        """Load + warm + atomically install `dirname` under `model`.
        `kind`: 'program' (save_inference_model dir), 'exported'
        (export_compiled_model dir), or 'auto' (sniff the artifact)."""
        model = str(model)
        # lint: allow-blocking — the whole deploy (load + per-bucket
        # compile + drain of the old engine) is deliberately serialized;
        # see _load_mu above. infer traffic never takes this lock.
        with self._load_mu:
            version = self._resolve_version(model, version)
            if kind == "auto":
                kind = ("exported"
                        if os.path.exists(os.path.join(
                            dirname, "__stablehlo__.bin"))
                        else "program")

            def build():
                if kind == "exported":
                    return InferenceEngine.from_exported_dir(
                        dirname, name=model, version=version,
                        max_queue=max_queue, max_wait_ms=max_wait_ms)
                return InferenceEngine.from_inference_dir(
                    dirname, name=model, version=version, buckets=buckets,
                    max_queue=max_queue, max_wait_ms=max_wait_ms)

            engine = self._registry.deploy(model, build)
            return engine.stats()

    def _unload_model(self, model: str) -> Dict[str, Any]:
        return self._registry.unload(str(model))

    def _list_models(self) -> Dict[str, Any]:
        return self._registry.stats()

    def _load_report(self) -> Dict[str, Any]:
        """Cheap structured load snapshot for capacity-aware routing
        (ISSUE 11 satellite). One dict per loaded model with the signal
        the FleetRouter balances on: free KV pages + live/max slots for
        decoders (the *Ragged Paged Attention* page-table view of
        remaining capacity), queue depth vs bound for both kinds, and
        the model/version set a rollout driver polls for convergence.
        A few lock-guarded dict reads per model — no Prometheus text to
        parse, no histogram walks — and declared idempotent so a
        router's scrape cadence never pins the dedup cache."""
        models: Dict[str, Any] = {}
        for name, st in self._registry.stats().items():
            entry: Dict[str, Any] = {
                "version": st["version"],
                "kind": st["kind"],
                "queue_depth": st["queue_depth"],
                "max_queue": st["max_queue"],
                "stopping": st["stopping"],
            }
            if st["kind"] == "decoder":
                kv = st["kv"]
                entry["free_pages"] = kv["pages_free"]
                entry["pages_total"] = kv["pages_total"]
                entry["page_size"] = kv["page_size"]
                entry["live_slots"] = st["live"]
                entry["max_slots"] = max(st["slots"])
                entry["max_seq_len"] = st["max_seq_len"]
                # speculative decoding (ISSUE 14): proposals per round
                # (0 = off) — lets operators see which replicas carry a
                # draft after a partial rollout
                entry["spec_k"] = st.get("spec_k", 0)
                # mesh-sharded replica (ISSUE 15): the axes this one
                # engine SPANS — operators and the fleet see which
                # replicas are multi-chip after a partial rollout
                if st.get("mesh"):
                    entry["mesh"] = st["mesh"]
                # prefix-cache warmth (ISSUE 13): the MRU depth-1
                # chain digests let a FleetRouter recognize a replica
                # whose cache already covers a request's prefix —
                # steps-to-first-token there is ceil(suffix/chunk),
                # not ceil(prompt/chunk)
                if st.get("prefix") is not None:
                    entry["prefix_cache"] = st["prefix"]
            models[name] = entry
        return {"ok": True, "models": models}

    def load_report(self) -> Dict[str, Any]:
        """In-process alias for the load_report RPC: the same snapshot,
        without a loopback dial — FleetMember piggybacks it on every
        heartbeat (ISSUE 17), and a beat must never block on its own
        server's RPC queue."""
        return self._load_report()

    def _health(self) -> Dict[str, Any]:
        return {"ok": True, "models": self._registry.names()}
