"""Paged KV cache — the HBM-bounded substrate of autoregressive decode.

The decode-serving problem (PAPERS.md, Ragged Paged Attention): every
live sequence needs its keys/values kept on-device, sequences have
ragged lengths that change every step, and a compiled TPU program
exists per SHAPE. Contiguous per-sequence KV buffers force a choice
between recompiling per ragged length (O(shapes) jit entries) or
padding every sequence to max length (HBM scales with max_len x
max_sequences even when traffic is short). Paging dissolves both:

  - K/V live in ONE preallocated pool of fixed-size pages
    (``[layers, pages, page_size, kv_heads, head_dim]``) — the HBM
    footprint is set at construction and never moves, no matter how
    ragged the traffic;
  - each sequence owns an ordered list of page ids (its PAGE TABLE);
    the attention kernel reads K/V *through* the table, so sequences
    of any length batch into one compiled shape per (slot-count,
    table-width) bucket;
  - pages return to a free list at completion and are reused — the
    allocator is the admission-control surface: when pages run out the
    refusal is an immediate structured ``ServerOverloaded``, never an
    OOM mid-decode.

Page 0 is RESERVED as the garbage page: dead decode slots and padded
page-table entries all point at it, so masked lanes in the batched
step have somewhere harmless to write/read without branching. The
allocator never hands it out.

PREFIX CACHING (ISSUE 13): the attention kernel only ever sees a page
TABLE, never ownership — so nothing stops two sequences' tables from
naming the same physical page. ``PrefixIndex`` exploits exactly that:
full prompt pages are published into a radix-over-pages index (each
entry keyed by a chained digest of its page's token content, so a
lookup walks the prompt page by page), refcounted, and IMMUTABLE from
publication on. A request whose prompt extends a cached chain maps the
shared pages read-only and prefills only its suffix; the partial tail
page is COPY-ON-WRITE — a mapper that needs to write into the page
region (its own suffix tokens, its decode tokens) gets a private
device copy, the shared page stays untouched. The last prompt token is
ALWAYS left to recompute (``cached <= len(prompt) - 1``): logits for
it come from running the model, not from cached K/V. Freed shared
pages stay in the index (refcount 0 = reclaimable, evicted LRU
leaf-first when the free list runs short) — ``pages_free`` counts them
as free because one eviction pass away is economically free.

RESERVATION (ISSUE 13): ``alloc`` still takes a worst-case token
count; demand-mode engines reserve only ``prompt + headroom`` and
``grow()`` one page at a time mid-decode — on exhaustion the ENGINE
preempts (spills a victim's pages to ``HostSpillStore``, frees them,
restores bitwise later), so admitted concurrency is priced by actual
token demand, not by the ``max_new_tokens`` long tail. The allocator's
refusals stay side-effect-free either way.
"""
from __future__ import annotations

import hashlib
import os
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import metrics as _metrics
from .errors import ServerOverloaded, ServingError

__all__ = ["PageAllocator", "PagedKvCache", "PrefixIndex",
           "HostSpillStore", "GARBAGE_PAGE", "PREFIX_ROOT", "chain_digest"]

# page id 0 is never allocated: dead slots / table padding target it
GARBAGE_PAGE = 0

_m_allocs = _metrics.counter("serving.kv.page_allocs")
_m_frees = _metrics.counter("serving.kv.page_frees")
_m_exhausted = _metrics.counter("serving.kv.exhaustions")
# prefix cache (ISSUE 13): hits/misses count REQUESTS (a hit mapped >=1
# cached token), cached_tokens counts prompt tokens answered from the
# index instead of prefilled, published counts pages that became
# shared, evictions counts cached pages reclaimed under pressure,
# cow_copies counts private copies of shared partial pages
_m_prefix_hits = _metrics.counter("serving.prefix.hits")
_m_prefix_misses = _metrics.counter("serving.prefix.misses")
_m_prefix_cached_tokens = _metrics.counter("serving.prefix.cached_tokens")
_m_prefix_published = _metrics.counter("serving.prefix.published_pages")
_m_prefix_evictions = _metrics.counter("serving.prefix.evictions")
_m_prefix_cow = _metrics.counter("serving.prefix.cow_copies")
# preemption spill traffic (ISSUE 13): pages/bytes that crossed to host
_m_spilled_pages = _metrics.counter("serving.kv.spilled_pages")
_m_spill_bytes = _metrics.counter("serving.kv.spill_bytes")
# speculative-decode rollback (ISSUE 14): pages that were grown for a
# verify chunk but ended up holding ONLY rejected tokens, returned to
# the free list by PageAllocator.shrink (the exact-pool invariant)
_m_shrunk_pages = _metrics.counter("serving.kv.shrunk_pages")
# one inc per TRACE of a fused page-move helper — i.e. one per distinct
# (pool shape, index count) the jitted gather/scatter/copy ops compile
# (the ROADMAP spill-economics residual: the helpers used to be eager
# whole-pool .at[].set updates; the counter proves repeat moves at the
# same shape re-use the executable)
_m_pagemove_compiles = _metrics.counter("serving.kv.pagemove_compiles")

# the root of every prefix chain; depth-1 entries hang off it
PREFIX_ROOT = "root"

# fused page-move executables (ISSUE 14 satellite): COW copies, spill
# gathers and restore scatters are jitted batched ops compiled once per
# (pool shape, page count) instead of eager whole-pool .at[].set
# updates — on TPU the copy/scatter donate the pools so XLA updates the
# pages in place. Built lazily (the backend must not initialize at
# import) and shared by every PagedKvCache in the process.
_page_move_mu = threading.Lock()
_PAGE_MOVE: Dict[str, Any] = {}  # guarded-by: _page_move_mu


def _page_move_fns() -> Dict[str, Any]:
    with _page_move_mu:
        if _PAGE_MOVE:
            return dict(_PAGE_MOVE)
        import jax

        # CPU ignores donation (and warns per call) — donate only where
        # it buys the in-place update, same as the decode step
        donate = jax.default_backend() == "tpu"

        # the .inc() calls run at TRACE time only: each fires once per
        # compiled shape, never per call — that IS the compiled-once
        # evidence the satellite test pins
        def copy_kv(k, v, src, dst):
            _m_pagemove_compiles.inc()
            return (k.at[:, dst].set(k[:, src]),
                    v.at[:, dst].set(v[:, src]))

        def gather_kv(k, v, idx):
            _m_pagemove_compiles.inc()
            return k[:, idx], v[:, idx]

        def scatter_kv(k, v, idx, ks, vs):
            _m_pagemove_compiles.inc()
            return (k.at[:, idx].set(ks.astype(k.dtype)),
                    v.at[:, idx].set(vs.astype(v.dtype)))

        _PAGE_MOVE["copy"] = jax.jit(
            copy_kv, donate_argnums=(0, 1) if donate else ())
        _PAGE_MOVE["gather"] = jax.jit(gather_kv)
        _PAGE_MOVE["scatter"] = jax.jit(
            scatter_kv, donate_argnums=(0, 1) if donate else ())
        return dict(_PAGE_MOVE)


def chain_digest(parent: str, tokens) -> str:
    """Chained content digest of one prompt page: H(parent digest ||
    token ids). Walking a prompt page by page through these digests IS
    the prefix lookup — equal digests mean equal token history, so a
    matching entry's K/V pages are exactly the K/V this prompt would
    have computed. Stable across processes (the fleet router computes
    the same digests client-side to find warm replicas)."""
    h = hashlib.blake2b(digest_size=16)
    h.update(parent.encode("utf-8"))
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.hexdigest()


class _PrefixEntry:
    __slots__ = ("key", "parent", "tokens", "page", "refs", "tick")

    def __init__(self, key: str, parent: str, tokens: Tuple[int, ...],
                 page: int):
        self.key = key
        self.parent = parent
        self.tokens = tokens
        self.page = page
        self.refs = 0       # live sequences whose table names this page
        self.tick = 0       # LRU recency (allocator's monotonic clock)


class PrefixIndex:
    """Radix-over-pages prefix index: one entry per published prompt
    page, keyed by ``chain_digest`` so lookups walk digest by digest
    from ``PREFIX_ROOT``. Entries are IMMUTABLE from publication
    (their pages are never written again; a would-be writer copies —
    the COW rule) and refcounted by the live sequences mapping them;
    refcount-0 entries are reclaimable, evicted LRU and LEAF-FIRST
    (a parent is only removable once childless, so a chain can never
    dangle mid-walk).

    NOT independently locked: every method is ``*_locked`` and runs
    under the OWNING allocator's mutex, which is shared in as
    ``self._mu`` so the guard declarations (and the runtime sanitizer)
    name the real lock."""

    def __init__(self, mu, page_size: int):
        self._mu = mu  # lint: lock-alias — the OWNING allocator's mutex
        self.page_size = int(page_size)
        self._entries: Dict[str, _PrefixEntry] = {}  # guarded-by: _mu
        # parent digest -> child entry keys (full and partial children)
        self._children: Dict[str, List[str]] = {}  # guarded-by: _mu
        self._by_page: Dict[int, str] = {}  # guarded-by: _mu
        self._tick = 0  # guarded-by: _mu
        # memoized evictable count: the full walk is O(entries x
        # depth) and sits on the per-step gauge-publish path — refs/
        # structure changes invalidate, per-step token accounting
        # (which changes neither) reuses the memo
        self._evictable: Optional[int] = None  # guarded-by: _mu

    def invalidate_locked(self):
        self._evictable = None

    # -- queries ----------------------------------------------------------
    def pages_retained_locked(self) -> int:
        return len(self._entries)

    def shared_pages_locked(self) -> int:
        """Pages mapped by two or more live sequences right now —
        refs >= 2 (publisher + at least one sharer, or several
        sharers). The allocator-counter proof that n-best/beam
        siblings (ISSUE 20) SHARE their prompt pages through the
        refcount rather than copying them."""
        return sum(1 for e in self._entries.values() if e.refs >= 2)

    def evictable_count_locked(self) -> int:
        """Entries a cascading leaf-first eviction could reclaim right
        now: refcount-0 entries with no referenced descendant (an
        ancestor of a live mapping must stay — the chain walk needs
        it). Memoized between refcount/structure changes (review
        finding: the walk ran once per decode STEP via the
        fragmentation gauge publish)."""
        if self._evictable is not None:
            return self._evictable
        keep: set = set()
        for key, e in self._entries.items():
            if e.refs <= 0:
                continue
            k = key
            while k != PREFIX_ROOT and k not in keep:
                keep.add(k)
                k = self._entries[k].parent
        self._evictable = len(self._entries) - len(keep)
        return self._evictable

    def match_locked(self, tokens: Sequence[int]
                     ) -> Tuple[List[_PrefixEntry],
                                Optional[Tuple[_PrefixEntry, int]]]:
        """Longest cached cover of ``tokens`` that still leaves >= 1
        token to recompute: ``(full shared entries, cow)`` where
        ``cow = (source entry, n_tokens)`` is the best partial-page
        extension (the caller device-copies the source page and trusts
        its first ``n_tokens`` offsets)."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        n = len(toks)
        matched: List[_PrefixEntry] = []
        parent = PREFIX_ROOT
        pos = 0
        # a full page is mappable read-only iff the request never
        # writes inside it: true while it ends at or before token n-2
        while pos + ps <= n - 1:
            key = chain_digest(parent, toks[pos:pos + ps])
            e = self._entries.get(key)
            if e is None or len(e.tokens) != ps or \
                    e.tokens != tuple(toks[pos:pos + ps]):
                break
            matched.append(e)
            parent = key
            pos += ps
        cow: Optional[Tuple[_PrefixEntry, int]] = None
        cap = (n - 1) - pos
        if cap > 0:
            best = 0
            for key in self._children.get(parent, ()):
                e = self._entries[key]
                lim = min(len(e.tokens), cap)
                m = 0
                while m < lim and e.tokens[m] == toks[pos + m]:
                    m += 1
                if m > best:
                    best, cow = m, (e, m)
        return matched, cow

    def roots_locked(self, cap: int = 32) -> List[str]:
        """Most-recently-used depth-1 entry digests — what a replica
        advertises in its load_report so the fleet router can tell a
        warm replica from a cold one without shipping the trie."""
        roots = [self._entries[k]
                 for k in self._children.get(PREFIX_ROOT, ())]
        roots.sort(key=lambda e: -e.tick)
        return [e.key for e in roots[:cap]]

    def cached_tokens_locked(self) -> int:
        return sum(len(e.tokens) for e in self._entries.values())

    # -- mutation ---------------------------------------------------------
    def touch_locked(self, e: _PrefixEntry):
        self._tick += 1
        e.tick = self._tick

    def publish_locked(self, pages: Sequence[int],
                       tokens: Sequence[int]) -> int:
        """Insert a completed prompt's pages: every full prompt page,
        plus the partial tail page (COW source for extenders). Pages
        whose chain digest already has an entry are skipped — the
        owner's private duplicate stays private and returns to the
        free list at its free(). From here on the inserted pages are
        immutable: their owner only ever writes positions PAST the
        published token range, and every other sequence either maps
        them read-only (full pages) or copies (the partial tail)."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        nfull = len(toks) // ps
        parent = PREFIX_ROOT
        created = 0
        for i in range(nfull):
            pt = tuple(toks[i * ps:(i + 1) * ps])
            key = chain_digest(parent, pt)
            e = self._entries.get(key)
            if e is None:
                if pages[i] in self._by_page:
                    # this page is already someone's published entry
                    # under a different chain — cannot happen for a
                    # privately-held page; defensive skip
                    break
                e = _PrefixEntry(key, parent, pt, pages[i])
                # the publisher still maps this page: it holds a ref
                # until its own free() (an unreffed entry would be
                # evictable while a live table names its page)
                e.refs = 1
                self._entries[key] = e
                self._children.setdefault(parent, []).append(key)
                self._by_page[pages[i]] = key
                created += 1
            elif e.tokens != pt:  # pragma: no cover - digest collision
                break
            self.touch_locked(e)
            parent = key
        tail = tuple(toks[nfull * ps:])
        if tail and nfull < len(pages) and \
                pages[nfull] not in self._by_page:
            if not any(self._entries[k].tokens == tail
                       for k in self._children.get(parent, ())):
                key = chain_digest(parent, tail)
                e = _PrefixEntry(key, parent, tail, pages[nfull])
                e.refs = 1  # the publisher's own mapping (see above)
                self._entries[key] = e
                self._children.setdefault(parent, []).append(key)
                self._by_page[pages[nfull]] = key
                self.touch_locked(e)
                created += 1
        if created:
            self.invalidate_locked()
        return created

    def release_page_locked(self, page: int) -> bool:
        """A sequence freed this page. True = the page belongs to a
        published entry and STAYS (refcount drops, LRU tick refreshed);
        False = private page, caller returns it to the free list."""
        key = self._by_page.get(page)
        if key is None:
            return False
        e = self._entries[key]
        e.refs = max(0, e.refs - 1)
        self.touch_locked(e)
        self.invalidate_locked()
        return True

    def evict_locked(self, want: int) -> List[int]:
        """Reclaim up to ``want`` pages: refcount-0 LEAVES first (a
        parent with children is structurally pinned), LRU among them.
        Returns the freed page ids."""
        out: List[int] = []
        while len(out) < want:
            best: Optional[_PrefixEntry] = None
            for key, e in self._entries.items():
                if e.refs == 0 and not self._children.get(key):
                    if best is None or e.tick < best.tick:
                        best = e
            if best is None:
                break
            self._entries.pop(best.key)
            self._by_page.pop(best.page, None)
            kids = self._children.get(best.parent)
            if kids is not None:
                kids.remove(best.key)
                if not kids:
                    self._children.pop(best.parent, None)
            self._children.pop(best.key, None)
            out.append(best.page)
            _m_prefix_evictions.inc()
            self.invalidate_locked()
        return out


class HostSpillStore:
    """Host-side refuge for a preempted sequence's KV pages (ISSUE 13).

    ``put`` parks the gathered page contents (bitwise — restore is an
    exact copy back), keyed by sequence id; ``pop`` surrenders them for
    restore; ``drop`` discards (cancel/deadline/retirement of a
    preempted sequence must leak nothing — spill files included).
    ``FLAGS['kv_spill_dir']`` (or the ``spill_dir`` argument) moves the
    payload to disk as one ``.npz`` per sequence — host RAM stays flat
    under heavy preemption; '' keeps spills in memory."""

    def __init__(self, spill_dir: Optional[str] = None,
                 label: Optional[str] = None):
        from ..fluid.flags import FLAGS

        self._dir = str(FLAGS["kv_spill_dir"]
                        if spill_dir is None else spill_dir)
        self._label = f"{label or 'kv'}-{uuid.uuid4().hex[:8]}"
        self._mu = threading.Lock()
        # seq_id -> (k, v) arrays, or the path holding them
        self._store: Dict[int, Any] = {}  # guarded-by: _mu

    def _path(self, seq_id: int) -> str:
        return os.path.join(self._dir,
                            f"kvspill-{self._label}-{int(seq_id)}.npz")

    def put(self, seq_id: int, *arrays: np.ndarray):
        """Park one preempted sequence's page contents: ``(k, v)`` for
        a plain decoder, ``(k, v, draft_k, draft_v)`` when a
        speculative draft's mirrored pool spills alongside (ISSUE 14 —
        same page ids, so one spill covers both pools)."""
        n_pages = int(arrays[0].shape[1])
        nbytes = int(sum(a.nbytes for a in arrays))
        if self._dir:
            # disk I/O outside the mutex: count()/stats() callers hold
            # the engine condition and must not stall on a slow savez
            os.makedirs(self._dir, exist_ok=True)
            ent: Any = self._path(seq_id)
            np.savez(ent, **{f"a{i}": a for i, a in enumerate(arrays)})
        else:
            ent = tuple(arrays)
        with self._mu:
            self._store[int(seq_id)] = ent
        _m_spilled_pages.inc(n_pages)
        _m_spill_bytes.inc(nbytes)

    def pop(self, seq_id: int) -> Optional[Tuple[np.ndarray, ...]]:
        with self._mu:
            ent = self._store.pop(int(seq_id), None)
        if ent is None:
            return None
        if isinstance(ent, str):
            with np.load(ent) as z:
                out = tuple(z[f"a{i}"] for i in range(len(z.files)))
            try:
                os.remove(ent)
            except OSError:  # pragma: no cover - already swept
                pass
            return out
        return ent

    def drop(self, seq_id: int) -> bool:
        with self._mu:
            ent = self._store.pop(int(seq_id), None)
        if isinstance(ent, str):
            try:
                os.remove(ent)
            except OSError:  # pragma: no cover - already swept
                pass
        return ent is not None

    def clear(self):
        with self._mu:
            ents = list(self._store.values())
            self._store.clear()
        for ent in ents:
            if isinstance(ent, str):
                try:
                    os.remove(ent)
                except OSError:  # pragma: no cover
                    pass

    def count(self) -> int:
        with self._mu:
            return len(self._store)


class PageAllocator:
    """Free-list page allocator over a fixed pool of ``num_pages``.

    Deterministic by construction (tested): fresh pages are handed out
    in ascending id order, freed pages are reused LIFO — the same
    admit/complete sequence always yields the same page tables, which
    is what makes decode runs replayable and the chaos tests exact.
    With ``prefix_cache=True`` an embedded ``PrefixIndex`` (same lock)
    retains published prompt pages for reuse; ``pages_free`` then
    counts reclaimable (refcount-0) cached pages as free, because one
    LRU eviction pass inside ``alloc`` turns them into free pages.

    Thread-safe via one internal lock; every operation under it is a
    list/dict edit (no blocking calls — L102-clean by construction).
    """

    def __init__(self, num_pages: int, page_size: int,
                 label: Optional[str] = None, prefix_cache: bool = False):
        if num_pages < 2:
            raise ValueError(
                f"need >= 2 pages (one is the reserved garbage page), "
                f"got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._mu = threading.Lock()
        # stack: pop() yields 1, 2, 3, ... when fresh; freed pages are
        # pushed on top and reused first (LIFO)
        self._free: List[int] = list(
            range(self.num_pages - 1, 0, -1))  # guarded-by: _mu
        self._owner: Dict[int, List[int]] = {}  # guarded-by: _mu
        self._tokens: Dict[int, int] = {}  # guarded-by: _mu
        self._total_tokens = 0  # guarded-by: _mu
        self.prefix = (PrefixIndex(self._mu, self.page_size)
                       if prefix_cache else None)
        # gauges are keyed per allocator when a label (engine name.vN)
        # is given — coexisting pools (hot-swap drain, multi-model)
        # must not last-writer-wins-clobber each other's occupancy;
        # the plain names serve the bare/single-allocator case
        sfx = f".{label}" if label else ""
        self._g_pages_total = _metrics.gauge(f"serving.kv.pages_total{sfx}")
        self._g_pages_used = _metrics.gauge(f"serving.kv.pages_used{sfx}")
        # fraction of ALLOCATED token capacity not (yet) holding a real
        # token — the price of reserve-at-admission, and the signal
        # that page_size is too coarse for the traffic's length mix
        self._g_fragmentation = _metrics.gauge(
            f"serving.kv.fragmentation{sfx}")
        # pages the prefix index retains (shared + reclaimable)
        self._g_prefix_pages = _metrics.gauge(
            f"serving.kv.prefix_pages{sfx}")
        self._g_pages_total.set(self.num_pages)
        # under the lock even here: _publish_locked reads the (already
        # armed) PrefixIndex, and the guard sanitizer rightly insists
        with self._mu:
            self._publish_locked()

    # -- introspection ----------------------------------------------------
    def _free_count_locked(self) -> int:
        """Free-list pages plus reclaimable (refcount-0, unpinned)
        cached pages — what an alloc can actually obtain."""
        n = len(self._free)
        if self.prefix is not None:
            n += self.prefix.evictable_count_locked()
        return n

    @property
    def pages_free(self) -> int:
        with self._mu:
            return self._free_count_locked()

    @property
    def pages_used(self) -> int:
        """Pages held by live sequences or pinned shared prefixes
        (excluding the reserved garbage page and reclaimable cache)."""
        with self._mu:
            return (self.num_pages - 1) - self._free_count_locked()

    def held_pages(self, seq_id: int) -> int:
        with self._mu:
            return len(self._owner.get(seq_id, ()))

    def pages_of(self, seq_id: int) -> List[int]:
        with self._mu:
            return list(self._owner.get(seq_id, ()))

    def stats(self) -> Dict[str, float]:
        with self._mu:
            free = self._free_count_locked()
            used = (self.num_pages - 1) - free
            toks = self._total_tokens
            cap = used * self.page_size
            out = {
                "pages_total": self.num_pages,
                "pages_used": used,
                "pages_free": free,
                "page_size": self.page_size,
                "sequences": len(self._owner),
                "tokens": toks,
                # shared pages enter cap once but their tokens can be
                # counted by several mappers: clamp at 0
                "fragmentation": (max(0.0, 1.0 - toks / cap)
                                  if cap else 0.0),
            }
            if self.prefix is not None:
                out["prefix_pages"] = self.prefix.pages_retained_locked()
                out["prefix_reclaimable"] = \
                    self.prefix.evictable_count_locked()
                out["prefix_shared_pages"] = \
                    self.prefix.shared_pages_locked()
            return out

    def prefix_stats(self, roots_cap: int = 32) -> Optional[Dict[str, Any]]:
        """The load_report view of this allocator's prefix cache: entry
        count, cached prompt tokens, and the MRU depth-1 chain digests
        a router matches request prefixes against. None when prefix
        caching is off."""
        if self.prefix is None:
            return None
        with self._mu:
            return {
                "pages": self.prefix.pages_retained_locked(),
                "tokens": self.prefix.cached_tokens_locked(),
                "page_size": self.page_size,
                "shared": self.prefix.shared_pages_locked(),
                "roots": self.prefix.roots_locked(roots_cap),
            }

    def _publish_locked(self):
        free = self._free_count_locked()
        used = (self.num_pages - 1) - free
        self._g_pages_used.set(used)
        toks = self._total_tokens
        cap = used * self.page_size
        self._g_fragmentation.set(
            round(max(0.0, 1.0 - toks / cap), 6) if cap else 0.0)
        if self.prefix is not None:
            self._g_prefix_pages.set(self.prefix.pages_retained_locked())

    def retire(self):
        """Zero this allocator's gauges (engine retirement) so a
        drained pool's final values don't linger as live occupancy."""
        with self._mu:
            self._g_pages_total.set(0)
            self._g_pages_used.set(0)
            self._g_fragmentation.set(0.0)
            self._g_prefix_pages.set(0)

    # -- lifecycle --------------------------------------------------------
    def pages_for_tokens(self, n_tokens: int) -> int:
        return max(1, -(-int(n_tokens) // self.page_size))

    def _take_locked(self, need: int, what: str) -> List[int]:
        """Pop ``need`` pages, reclaiming LRU refcount-0 prefix pages
        when the free list alone is short. Raises side-effect-free on
        the FREE LIST (evicted cache entries stay evicted — they were
        reclaimable by definition)."""
        if need > len(self._free) and self.prefix is not None:
            self._free.extend(
                self.prefix.evict_locked(need - len(self._free)))
        if need > len(self._free):
            _m_exhausted.inc()
            raise ServerOverloaded(
                f"KV page pool exhausted: need {need} pages for "
                f"{what}, {len(self._free)} of "
                f"{self.num_pages - 1} free — retry later, raise "
                f"kv_num_pages, or shed to another replica")
        return [self._free.pop() for _ in range(need)]

    def alloc(self, seq_id: int, n_tokens: int) -> List[int]:
        """Reserve pages for a sequence of up to ``n_tokens``. Raises
        ``ServerOverloaded`` (the pool IS the admission bound) without
        side effects when short."""
        need = self.pages_for_tokens(n_tokens)
        with self._mu:
            if seq_id in self._owner:
                raise ValueError(f"sequence {seq_id} already has pages")
            pages = self._take_locked(need, f"{n_tokens} tokens")
            self._owner[seq_id] = pages
            self._tokens[seq_id] = 0
            _m_allocs.inc(need)
            self._publish_locked()
            return list(pages)

    def alloc_prefix(self, seq_id: int, prompt: Sequence[int],
                     reserve_tokens: int) -> Dict[str, Any]:
        """Prefix-aware reservation: map the longest cached chain of
        ``prompt``'s full pages read-only (refcounted), pick the best
        COW source for the partial tail, and take fresh pages for the
        rest of ``reserve_tokens``. Returns ``{"pages", "cached_tokens",
        "cow"}`` where ``cow = {"key", "src", "dst", "tokens"}`` names
        the device copy the ENGINE must perform before the sequence's
        first step (the source entry is reffed until ``release_cow`` so
        eviction can't yank it mid-copy). Falls back to a plain miss
        when prefix caching is off."""
        prompt = [int(t) for t in prompt]
        with self._mu:
            if seq_id in self._owner:
                raise ValueError(f"sequence {seq_id} already has pages")
            matched: List[_PrefixEntry] = []
            cow = None
            if self.prefix is not None:
                matched, cow = self.prefix.match_locked(prompt)
            cached = len(matched) * self.page_size + \
                (cow[1] if cow else 0)
            need_total = self.pages_for_tokens(
                max(int(reserve_tokens), len(prompt)))
            # the COW destination is a fresh page; shared pages cover
            # the first len(matched) table slots
            fresh_need = max(1, need_total - len(matched))
            # pin the matched chain and the COW source BEFORE taking
            # fresh pages: _take_locked may evict refcount-0 entries,
            # and without the pin it could reclaim a page of the very
            # chain we just matched and hand it back as "fresh" —
            # one physical page aliased into two table slots
            # (review finding; unpinned again on refusal, so the
            # raise stays side-effect-free on refcounts)
            pinned = list(matched)
            if cow is not None:
                pinned.append(cow[0])
            for e in pinned:
                e.refs += 1
                self.prefix.touch_locked(e)
            if pinned:
                self.prefix.invalidate_locked()
            try:
                fresh = self._take_locked(
                    fresh_need, f"{reserve_tokens} tokens "
                    f"({cached} cached)")
            except ServerOverloaded:
                for e in pinned:
                    e.refs = max(0, e.refs - 1)
                if pinned:
                    self.prefix.invalidate_locked()
                raise
            cow_out = None
            if cow is not None:
                src, n = cow
                cow_out = {"key": src.key, "src": src.page,
                           "dst": fresh[0], "tokens": n}
            pages = [e.page for e in matched] + fresh
            self._owner[seq_id] = pages
            self._tokens[seq_id] = cached
            self._total_tokens += cached
            _m_allocs.inc(fresh_need)
            if cached:
                _m_prefix_hits.inc()
                _m_prefix_cached_tokens.inc(cached)
                _m_prefix_cow.inc(1 if cow_out else 0)
            elif self.prefix is not None:
                _m_prefix_misses.inc()
            self._publish_locked()
            return {"pages": list(pages), "cached_tokens": cached,
                    "cow": cow_out}

    def release_cow(self, key: str):
        """Drop the pin ``alloc_prefix`` took on a COW source entry —
        called once the device copy landed (or the request died before
        it could)."""
        with self._mu:
            if self.prefix is None:
                return
            e = self.prefix._entries.get(key)
            if e is not None:
                e.refs = max(0, e.refs - 1)
                self.prefix.touch_locked(e)
                self.prefix.invalidate_locked()

    def grow(self, seq_id: int, n_pages: int = 1) -> List[int]:
        """Extend a live sequence's reservation (demand-mode decode:
        the engine grows one page at a time as generation crosses page
        boundaries). All-or-nothing and side-effect-free on refusal —
        the engine answers a refusal with preemption, never a partial
        grant."""
        with self._mu:
            if seq_id not in self._owner:
                raise ValueError(f"sequence {seq_id} holds no pages")
            pages = self._take_locked(int(n_pages),
                                      f"growth of seq {seq_id}")
            self._owner[seq_id].extend(pages)
            _m_allocs.inc(len(pages))
            self._publish_locked()
            return pages

    def shrink(self, seq_id: int, n_pages: int) -> int:
        """Return the LAST ``n_pages`` of a live sequence's reservation
        to the free list — the speculative-decode rollback (ISSUE 14):
        a verify chunk grows the reservation to cover ``k+1`` writes,
        and a page that ended up holding ONLY rejected tokens must not
        stay reserved (the exact-pool invariant). Tail pages during
        decode are always private fresh pages, but each popped page
        still routes through the prefix-release check defensively.
        Returns how many pages were actually freed (capped so the
        sequence always keeps >= 1 page)."""
        with self._mu:
            pages = self._owner.get(seq_id)
            if pages is None:
                raise ValueError(f"sequence {seq_id} holds no pages")
            take = max(0, min(int(n_pages), len(pages) - 1))
            freed = 0
            for _ in range(take):
                p = pages.pop()
                if self.prefix is not None and \
                        self.prefix.release_page_locked(p):
                    continue  # pragma: no cover - published tail page
                self._free.append(p)
                freed += 1
            if freed:
                _m_shrunk_pages.inc(freed)
                _m_frees.inc(freed)
                self._publish_locked()
            return freed

    def publish(self, seq_id: int, prompt: Sequence[int]) -> int:
        """Publish a sequence's completed prompt pages into the prefix
        index (no-op without prefix caching). Metadata only — the K/V
        bytes are already on-device; from here those pages are
        immutable and shareable."""
        with self._mu:
            if self.prefix is None or seq_id not in self._owner:
                return 0
            n = self.prefix.publish_locked(self._owner[seq_id], prompt)
            if n:
                _m_prefix_published.inc(n)
                self._publish_locked()
            return n

    def reserved_tokens(self, seq_id: int) -> int:
        """Token capacity of the sequence's reservation (held pages x
        page_size). Appends — single decode tokens AND multi-token
        prefill chunks alike — always land inside this bound; it grows
        only through an explicit ``grow()`` (demand mode), never as a
        side effect of a step (the chunked-prefill invariant test
        reads it)."""
        with self._mu:
            return len(self._owner.get(seq_id, ())) * self.page_size

    def note_tokens(self, seq_id: int, n_tokens: int):
        """Record how many tokens the sequence has actually written —
        feeds the fragmentation gauge; never moves pages."""
        self.note_tokens_many({seq_id: n_tokens})

    def note_tokens_many(self, updates: Dict[int, int]):
        """Batched ``note_tokens`` for a whole decode step: one lock
        acquisition and one gauge publish for all live slots (the
        per-step hot path must not take the lock once per slot).
        Unknown (already freed) sequences are skipped."""
        with self._mu:
            changed = False
            for seq_id, n_tokens in updates.items():
                if seq_id in self._tokens:
                    n = int(n_tokens)
                    self._total_tokens += n - self._tokens[seq_id]
                    self._tokens[seq_id] = n
                    changed = True
            if changed:
                self._publish_locked()

    def free(self, seq_id: int) -> int:
        """Return a sequence's pages: private pages go back to the free
        list (LIFO reuse), published shared pages stay in the prefix
        index with their refcount dropped (refcount 0 = reclaimable).
        Idempotent: freeing an unknown sequence is a no-op (the
        completion path and an abort path may race)."""
        with self._mu:
            pages = self._owner.pop(seq_id, None)
            self._total_tokens -= self._tokens.pop(seq_id, 0)
            if not pages:
                return 0
            freed = 0
            # reversed: re-allocating immediately yields the same ids in
            # the same order the sequence held them (determinism test)
            for p in reversed(pages):
                if self.prefix is not None and \
                        self.prefix.release_page_locked(p):
                    continue
                self._free.append(p)
                freed += 1
            if freed:
                _m_frees.inc(freed)
            self._publish_locked()
            return freed

    def _fill_row_locked(self, seq_id: int, out: np.ndarray):
        pages = self._owner.get(seq_id, [])
        if len(pages) > out.shape[0]:
            raise ValueError(
                f"sequence {seq_id} holds {len(pages)} pages, table "
                f"width bucket {out.shape[0]} too narrow")
        out[:len(pages)] = pages

    def table_row(self, seq_id: int, width: int) -> np.ndarray:
        """The sequence's page table padded to ``width`` with the
        garbage page — the row shape is a COMPILED shape, so padding
        happens here, once, deterministically."""
        with self._mu:
            row = np.full((width,), GARBAGE_PAGE, dtype=np.int32)
            self._fill_row_locked(seq_id, row)
            return row

    def table_rows(self, seq_ids: Sequence[int], width: int,
                   rows: int) -> np.ndarray:
        """Stacked padded page tables ``[rows, width]`` for a whole
        decode batch under ONE lock acquisition — the per-step hot
        path must not take the allocator lock once per live slot."""
        out = np.full((int(rows), width), GARBAGE_PAGE, dtype=np.int32)
        with self._mu:
            for i, sid in enumerate(seq_ids):
                self._fill_row_locked(sid, out[i])
        return out


class PagedKvCache:
    """The device-side pool the allocator's page ids index into.

    K and V are each ``[layers, pages, page_size, kv_heads, head_dim]``
    jax arrays allocated ONCE — ``hbm_bytes`` is the whole KV budget of
    the engine, independent of how ragged the traffic is. The decode
    step threads the pools through functionally (donated on TPU so XLA
    updates them in place); the cache object rebinds after each step.

    The page-move helpers (``copy_pages`` for COW, ``gather_pages`` /
    ``scatter_pages`` for preemption spill/restore) also rebind — the
    ENGINE serializes them with live steps under its step mutex, the
    same discipline ``warm()`` follows.
    """

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int,
                 *, page_size: int, num_pages: int, dtype=None,
                 label: Optional[str] = None, prefix_cache: bool = False,
                 allocator: Optional[PageAllocator] = None,
                 mesh=None, shard_spec=None):
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        # a speculative DRAFT pool (ISSUE 14) MIRRORS its target's page
        # geometry: pass the target's allocator and the two pools share
        # one set of page ids/tables — one reservation, one free, one
        # set of occupancy gauges; only the per-page payload shape
        # (layers/heads/dim) differs
        if allocator is not None:
            if (allocator.num_pages != int(num_pages)
                    or allocator.page_size != int(page_size)):
                raise ValueError(
                    f"shared allocator geometry "
                    f"({allocator.num_pages}x{allocator.page_size}) != "
                    f"pool geometry ({num_pages}x{page_size})")
            self.allocator = allocator
        else:
            self.allocator = PageAllocator(num_pages, page_size,
                                           label=label,
                                           prefix_cache=prefix_cache)
        self.dtype = jnp.float32 if dtype is None else dtype
        shape = (self.num_layers, int(num_pages), int(page_size),
                 self.num_kv_heads, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        # mesh-sharded pools (ISSUE 15): one decode replica spans chips
        # with the pool sharded over the kv-head axis — hbm_bytes stays
        # the GLOBAL budget, each chip holds 1/|axis| of it. `sharding`
        # is the pinned NamedSharding every rebind conforms to, so a
        # page-move helper's output can never drift the step's input
        # sharding (which would mint a post-warm compile).
        self.sharding = None
        if mesh is not None and shard_spec is not None:
            import jax
            from jax.sharding import NamedSharding

            self.sharding = NamedSharding(mesh, shard_spec)
            self.k = jax.device_put(self.k, self.sharding)
            self.v = jax.device_put(self.v, self.sharding)

    @property
    def page_size(self) -> int:
        return self.allocator.page_size

    @property
    def num_pages(self) -> int:
        return self.allocator.num_pages

    @property
    def hbm_bytes(self) -> int:
        """The preallocated KV budget: fixed at construction."""
        return 2 * int(np.prod(self.k.shape)) * self.k.dtype.itemsize

    def rebind(self, k, v):
        """Adopt the pools a decode step returned. Shape-checked: the
        whole point is that the footprint NEVER changes."""
        if tuple(k.shape) != tuple(self.k.shape) or \
                tuple(v.shape) != tuple(self.v.shape):
            raise ValueError(
                f"decode step changed the pool shape: "
                f"{tuple(self.k.shape)} -> {tuple(k.shape)}")
        if self.sharding is not None:
            # conform to the pinned sharding: the decode steps already
            # come back pinned (out_shardings), but the jitted page-move
            # helpers let GSPMD choose — a drifted pool would change the
            # next step's input sharding and mint a post-warm compile.
            # device_put to an identical sharding is a no-op.
            import jax

            if getattr(k, "sharding", None) != self.sharding:
                k = jax.device_put(k, self.sharding)
            if getattr(v, "sharding", None) != self.sharding:
                v = jax.device_put(v, self.sharding)
        self.k = k
        self.v = v

    def copy_pages(self, pairs: Sequence[Tuple[int, int]]):
        """Copy-on-write: duplicate page contents src -> dst in one
        jitted batched update, compiled once per (pool shape, pair
        count) — the ROADMAP spill-economics residual replaced the
        eager whole-pool ``.at[].set`` form (whole pages either way:
        the mapper trusts only the published token offsets and
        overwrites the rest itself). Caller holds the engine's step
        mutex."""
        if not pairs:
            return
        if self.k is None:
            raise ServingError("KV pools released — engine retired")
        srcs = np.asarray([p[0] for p in pairs], np.int32)
        dsts = np.asarray([p[1] for p in pairs], np.int32)
        self.k, self.v = _page_move_fns()["copy"](self.k, self.v,
                                                  srcs, dsts)

    def gather_pages(self, pages: Sequence[int]
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Read page contents to host (preemption spill): bitwise
        copies of ``[layers, len(pages), page_size, heads, dim]`` via
        the jitted batched gather (one executable per page count, not
        one whole-pool slice per call)."""
        if self.k is None:
            raise ServingError("KV pools released — engine retired")
        idx = np.asarray(list(pages), np.int32)
        k, v = _page_move_fns()["gather"](self.k, self.v, idx)
        return np.asarray(k), np.asarray(v)

    def scatter_pages(self, pages: Sequence[int], k: np.ndarray,
                      v: np.ndarray):
        """Write spilled page contents back (preemption restore) —
        the bitwise inverse of ``gather_pages``, into a possibly
        DIFFERENT set of physical pages (the table rebinds; content,
        not placement, is what round-trips). Same jitted batched
        scatter, donated in place on TPU."""
        if self.k is None:
            raise ServingError("KV pools released — engine retired")
        idx = np.asarray(list(pages), np.int32)
        if k.shape[1] != idx.shape[0]:
            raise ServingError(
                f"spill restore shape mismatch: {k.shape[1]} spilled "
                f"pages vs {idx.shape[0]} target pages")
        self.k, self.v = _page_move_fns()["scatter"](self.k, self.v,
                                                     idx, k, v)

    def table_array(self, seq_ids: Sequence[int], width: int,
                    rows: Optional[int] = None) -> np.ndarray:
        """Stacked page tables for a decode batch: ``[rows, width]``
        int32, dead rows (beyond ``seq_ids``) all-garbage."""
        n = len(seq_ids) if rows is None else int(rows)
        return self.allocator.table_rows(seq_ids, width, n)

    def release(self):
        """Drop the device pools (engine retirement) so HBM frees, and
        zero the allocator's gauges so the dead pool stops reporting."""
        self.k = None
        self.v = None
        self.allocator.retire()
