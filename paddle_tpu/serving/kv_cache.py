"""Paged KV cache — the HBM-bounded substrate of autoregressive decode.

The decode-serving problem (PAPERS.md, Ragged Paged Attention): every
live sequence needs its keys/values kept on-device, sequences have
ragged lengths that change every step, and a compiled TPU program
exists per SHAPE. Contiguous per-sequence KV buffers force a choice
between recompiling per ragged length (O(shapes) jit entries) or
padding every sequence to max length (HBM scales with max_len x
max_sequences even when traffic is short). Paging dissolves both:

  - K/V live in ONE preallocated pool of fixed-size pages
    (``[layers, pages, page_size, kv_heads, head_dim]``) — the HBM
    footprint is set at construction and never moves, no matter how
    ragged the traffic;
  - each sequence owns an ordered list of page ids (its PAGE TABLE);
    the attention kernel reads K/V *through* the table, so sequences
    of any length batch into one compiled shape per (slot-count,
    table-width) bucket;
  - pages return to a free list at completion and are reused — the
    allocator is the admission-control surface: when pages run out the
    refusal is an immediate structured ``ServerOverloaded``, never an
    OOM mid-decode.

Page 0 is RESERVED as the garbage page: dead decode slots and padded
page-table entries all point at it, so masked lanes in the batched
step have somewhere harmless to write/read without branching. The
allocator never hands it out.

Allocation policy: a sequence's worst-case page count
(``ceil((prompt + max_new_tokens) / page_size)``) is allocated up
front at admission. Pages are just indices into HBM that is already
paid for, so reserving them early costs nothing physical — and it
means a sequence that was admitted can NEVER die of page exhaustion
mid-decode; the only refusal point is admission, where the client
gets a typed reject it can retry against another replica. The cost is
internal fragmentation (allocated-but-unwritten token slots), which
the ``serving.kv.fragmentation`` gauge makes visible.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import metrics as _metrics
from .errors import ServerOverloaded

__all__ = ["PageAllocator", "PagedKvCache", "GARBAGE_PAGE"]

# page id 0 is never allocated: dead slots / table padding target it
GARBAGE_PAGE = 0

_m_allocs = _metrics.counter("serving.kv.page_allocs")
_m_frees = _metrics.counter("serving.kv.page_frees")
_m_exhausted = _metrics.counter("serving.kv.exhaustions")


class PageAllocator:
    """Free-list page allocator over a fixed pool of ``num_pages``.

    Deterministic by construction (tested): fresh pages are handed out
    in ascending id order, freed pages are reused LIFO — the same
    admit/complete sequence always yields the same page tables, which
    is what makes decode runs replayable and the chaos tests exact.

    Thread-safe via one internal lock; every operation under it is a
    list/dict edit (no blocking calls — L102-clean by construction).
    """

    def __init__(self, num_pages: int, page_size: int,
                 label: Optional[str] = None):
        if num_pages < 2:
            raise ValueError(
                f"need >= 2 pages (one is the reserved garbage page), "
                f"got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._mu = threading.Lock()
        # stack: pop() yields 1, 2, 3, ... when fresh; freed pages are
        # pushed on top and reused first (LIFO)
        self._free: List[int] = list(
            range(self.num_pages - 1, 0, -1))  # guarded-by: _mu
        self._owner: Dict[int, List[int]] = {}  # guarded-by: _mu
        self._tokens: Dict[int, int] = {}  # guarded-by: _mu
        self._total_tokens = 0  # guarded-by: _mu
        # gauges are keyed per allocator when a label (engine name.vN)
        # is given — coexisting pools (hot-swap drain, multi-model)
        # must not last-writer-wins-clobber each other's occupancy;
        # the plain names serve the bare/single-allocator case
        sfx = f".{label}" if label else ""
        self._g_pages_total = _metrics.gauge(f"serving.kv.pages_total{sfx}")
        self._g_pages_used = _metrics.gauge(f"serving.kv.pages_used{sfx}")
        # fraction of ALLOCATED token capacity not (yet) holding a real
        # token — the price of reserve-at-admission, and the signal
        # that page_size is too coarse for the traffic's length mix
        self._g_fragmentation = _metrics.gauge(
            f"serving.kv.fragmentation{sfx}")
        self._g_pages_total.set(self.num_pages)
        self._publish_locked()

    # -- introspection ----------------------------------------------------
    @property
    def pages_free(self) -> int:
        with self._mu:
            return len(self._free)

    @property
    def pages_used(self) -> int:
        """Allocated pages (excluding the reserved garbage page)."""
        with self._mu:
            return (self.num_pages - 1) - len(self._free)

    def stats(self) -> Dict[str, float]:
        with self._mu:
            used = (self.num_pages - 1) - len(self._free)
            toks = self._total_tokens
            cap = used * self.page_size
            return {
                "pages_total": self.num_pages,
                "pages_used": used,
                "pages_free": len(self._free),
                "page_size": self.page_size,
                "sequences": len(self._owner),
                "tokens": toks,
                "fragmentation": (1.0 - toks / cap) if cap else 0.0,
            }

    def _publish_locked(self):
        used = (self.num_pages - 1) - len(self._free)
        self._g_pages_used.set(used)
        toks = self._total_tokens
        cap = used * self.page_size
        self._g_fragmentation.set(
            round(1.0 - toks / cap, 6) if cap else 0.0)

    def retire(self):
        """Zero this allocator's gauges (engine retirement) so a
        drained pool's final values don't linger as live occupancy."""
        with self._mu:
            self._g_pages_total.set(0)
            self._g_pages_used.set(0)
            self._g_fragmentation.set(0.0)

    # -- lifecycle --------------------------------------------------------
    def pages_for_tokens(self, n_tokens: int) -> int:
        return max(1, -(-int(n_tokens) // self.page_size))

    def alloc(self, seq_id: int, n_tokens: int) -> List[int]:
        """Reserve the worst-case page count for a sequence of up to
        ``n_tokens``. Raises ``ServerOverloaded`` (the pool IS the
        admission bound) without side effects when short."""
        need = self.pages_for_tokens(n_tokens)
        with self._mu:
            if seq_id in self._owner:
                raise ValueError(f"sequence {seq_id} already has pages")
            if need > len(self._free):
                _m_exhausted.inc()
                raise ServerOverloaded(
                    f"KV page pool exhausted: need {need} pages for "
                    f"{n_tokens} tokens, {len(self._free)} of "
                    f"{self.num_pages - 1} free — retry later, raise "
                    f"kv_num_pages, or shed to another replica")
            pages = [self._free.pop() for _ in range(need)]
            self._owner[seq_id] = pages
            self._tokens[seq_id] = 0
            _m_allocs.inc(need)
            self._publish_locked()
            return list(pages)

    def reserved_tokens(self, seq_id: int) -> int:
        """Token capacity of the sequence's reservation (held pages x
        page_size). Reserve-at-admission means appends — single decode
        tokens AND multi-token prefill chunks alike — always land
        inside this bound; it never grows after ``alloc`` (the
        chunked-prefill invariant test reads it)."""
        with self._mu:
            return len(self._owner.get(seq_id, ())) * self.page_size

    def note_tokens(self, seq_id: int, n_tokens: int):
        """Record how many tokens the sequence has actually written —
        feeds the fragmentation gauge; never moves pages."""
        self.note_tokens_many({seq_id: n_tokens})

    def note_tokens_many(self, updates: Dict[int, int]):
        """Batched ``note_tokens`` for a whole decode step: one lock
        acquisition and one gauge publish for all live slots (the
        per-step hot path must not take the lock once per slot).
        Unknown (already freed) sequences are skipped."""
        with self._mu:
            changed = False
            for seq_id, n_tokens in updates.items():
                if seq_id in self._tokens:
                    n = int(n_tokens)
                    self._total_tokens += n - self._tokens[seq_id]
                    self._tokens[seq_id] = n
                    changed = True
            if changed:
                self._publish_locked()

    def free(self, seq_id: int) -> int:
        """Return a sequence's pages to the free list (LIFO reuse).
        Idempotent: freeing an unknown sequence is a no-op (the
        completion path and an abort path may race)."""
        with self._mu:
            pages = self._owner.pop(seq_id, None)
            self._total_tokens -= self._tokens.pop(seq_id, 0)
            if not pages:
                return 0
            # reversed: re-allocating immediately yields the same ids in
            # the same order the sequence held them (determinism test)
            self._free.extend(reversed(pages))
            _m_frees.inc(len(pages))
            self._publish_locked()
            return len(pages)

    def _fill_row_locked(self, seq_id: int, out: np.ndarray):
        pages = self._owner.get(seq_id, [])
        if len(pages) > out.shape[0]:
            raise ValueError(
                f"sequence {seq_id} holds {len(pages)} pages, table "
                f"width bucket {out.shape[0]} too narrow")
        out[:len(pages)] = pages

    def table_row(self, seq_id: int, width: int) -> np.ndarray:
        """The sequence's page table padded to ``width`` with the
        garbage page — the row shape is a COMPILED shape, so padding
        happens here, once, deterministically."""
        with self._mu:
            row = np.full((width,), GARBAGE_PAGE, dtype=np.int32)
            self._fill_row_locked(seq_id, row)
            return row

    def table_rows(self, seq_ids: Sequence[int], width: int,
                   rows: int) -> np.ndarray:
        """Stacked padded page tables ``[rows, width]`` for a whole
        decode batch under ONE lock acquisition — the per-step hot
        path must not take the allocator lock once per live slot."""
        out = np.full((int(rows), width), GARBAGE_PAGE, dtype=np.int32)
        with self._mu:
            for i, sid in enumerate(seq_ids):
                self._fill_row_locked(sid, out[i])
        return out


class PagedKvCache:
    """The device-side pool the allocator's page ids index into.

    K and V are each ``[layers, pages, page_size, kv_heads, head_dim]``
    jax arrays allocated ONCE — ``hbm_bytes`` is the whole KV budget of
    the engine, independent of how ragged the traffic is. The decode
    step threads the pools through functionally (donated on TPU so XLA
    updates them in place); the cache object rebinds after each step.
    """

    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int,
                 *, page_size: int, num_pages: int, dtype=None,
                 label: Optional[str] = None):
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.allocator = PageAllocator(num_pages, page_size, label=label)
        self.dtype = jnp.float32 if dtype is None else dtype
        shape = (self.num_layers, int(num_pages), int(page_size),
                 self.num_kv_heads, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)

    @property
    def page_size(self) -> int:
        return self.allocator.page_size

    @property
    def num_pages(self) -> int:
        return self.allocator.num_pages

    @property
    def hbm_bytes(self) -> int:
        """The preallocated KV budget: fixed at construction."""
        return 2 * int(np.prod(self.k.shape)) * self.k.dtype.itemsize

    def rebind(self, k, v):
        """Adopt the pools a decode step returned. Shape-checked: the
        whole point is that the footprint NEVER changes."""
        if tuple(k.shape) != tuple(self.k.shape) or \
                tuple(v.shape) != tuple(self.v.shape):
            raise ValueError(
                f"decode step changed the pool shape: "
                f"{tuple(self.k.shape)} -> {tuple(k.shape)}")
        self.k = k
        self.v = v

    def table_array(self, seq_ids: Sequence[int], width: int,
                    rows: Optional[int] = None) -> np.ndarray:
        """Stacked page tables for a decode batch: ``[rows, width]``
        int32, dead rows (beyond ``seq_ids``) all-garbage."""
        n = len(seq_ids) if rows is None else int(rows)
        return self.allocator.table_rows(seq_ids, width, n)

    def release(self):
        """Drop the device pools (engine retirement) so HBM frees, and
        zero the allocator's gauges so the dead pool stops reporting."""
        self.k = None
        self.v = None
        self.allocator.retire()
