"""CLI driver for the serving subsystem.

    python -m paddle_tpu.serving --selftest
        In-process end-to-end proof (no external network, no datasets):
        builds two versions of a tiny model, then exercises the bucketed
        batcher (jit-compile bound + batch-invariance), the RPC
        server/client path, an atomic hot-swap, the overload rejection
        path, the DECODE path (ISSUE 6: paged-KV continuous
        batching — warmed slot/width ladder, zero churn compiles, page
        exhaustion refusal, RPC generate + decoder hot-swap), the
        ISSUE 13 layer (prefix-cache hits prefill only the suffix;
        demand reservation + preempt/restore completes an over-
        committed pool with reference-equal tokens), and the ISSUE 14
        layer (speculative decoding: draft-propose + chunked-verify
        emits bitwise the non-speculative tokens — greedy AND seeded
        sampling — in fewer target steps, zero post-warm compiles,
        every rollback page returned).
        Exit-nonzero on any failure — wired into tools/check.py as the
        serving smoke.

    python -m paddle_tpu.serving --serve --load m=/path/to/model_dir
        Operator mode: start a ServingServer, load the named model
        directories, print the address, and serve until interrupted.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile


def _force_cpu():
    """The selftest must not require (or try to dial) a TPU: pin the jax
    platform before any backend initialization, the same way
    tests/conftest.py and the analysis CLI do."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def make_model_dir(dirname: str, scale: float = 1.0, feature_dim: int = 8,
                   classes: int = 3):
    """Build + save a tiny softmax model with DETERMINISTIC,
    scale-distinct parameters (so two builds with different `scale` are
    observably different model versions). Returns (dirname, probe
    input, reference output) — the reference computed by the framework
    itself, for later equality checks against the serving path."""
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, unique_name
    from paddle_tpu.fluid.framework import Parameter, Program, program_guard

    main, startup, scope = Program(), Program(), fluid.Scope()
    with fluid.scope_guard(scope):
        with program_guard(main, startup), unique_name.guard():
            x = layers.data(name="x", shape=[feature_dim], dtype="float32")
            pred = layers.fc(input=x, size=classes, act="softmax")
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(7)
        for var in sorted(main.list_vars(), key=lambda v: v.name):
            if isinstance(var, Parameter):
                vals = rng.uniform(-1, 1, size=tuple(var.shape)) * scale
                scope.set_var(var.name, jnp.asarray(vals.astype(np.float32)))
        fluid.io.save_inference_model(dirname, ["x"], [pred], exe, main)
        probe = np.random.RandomState(11).rand(4, feature_dim).astype(
            np.float32)
        (ref,) = exe.run(main, feed={"x": probe}, fetch_list=[pred])
    return dirname, probe, ref


def run_selftest(verbose: bool = True) -> int:
    import numpy as np

    from concurrent.futures import ThreadPoolExecutor

    from paddle_tpu.observability import metrics as _metrics
    from . import (InferenceEngine, ServerOverloaded, ServingClient,
                   ServingServer)

    def say(msg):
        if verbose:
            print(f"  {msg}")

    failures = []

    def check(ok, what):
        say(("ok  " if ok else "FAIL") + f" {what}")
        if not ok:
            failures.append(what)

    with tempfile.TemporaryDirectory() as tmp:
        d1, probe, ref1 = make_model_dir(os.path.join(tmp, "v1"), scale=1.0)
        d2, _, ref2 = make_model_dir(os.path.join(tmp, "v2"), scale=-1.0)

        # -- 1. bucketed batching bounds the jit cache -------------------
        jc = _metrics.counter("executor.jit_compiles")
        base = jc.value()
        eng = InferenceEngine.from_inference_dir(
            d1, name="selftest", buckets=[1, 2, 4], max_wait_ms=1.0)
        warm_compiles = jc.value() - base
        check(warm_compiles <= 3,
              f"warmup compiles {warm_compiles} <= ladder length 3")
        sizes = [1, 2, 3, 4, 1, 3, 2, 4, 1, 1]
        rng = np.random.RandomState(0)
        reqs = [rng.rand(b, 8).astype(np.float32) for b in sizes]
        with ThreadPoolExecutor(max_workers=6) as pool:
            outs = list(pool.map(lambda a: eng.infer({"x": a}), reqs))
        check(all(o[0][0].shape[0] == a.shape[0]
                  for o, a in zip(outs, reqs)),
              "every request got its own rows back")
        check(jc.value() - base <= 3,
              f"mixed arrival pattern stayed inside the ladder "
              f"({jc.value() - base} compiles)")
        # batch invariance: one 4-row request == 4 single-row requests
        (whole, _) = eng.infer({"x": probe})
        singles = [eng.infer({"x": probe[i:i + 1]})[0][0]
                   for i in range(probe.shape[0])]
        check(np.allclose(np.concatenate(singles), whole[0], atol=1e-5),
              "batching is result-invariant (padding sliced off)")
        check(np.allclose(whole[0], ref1, atol=1e-5),
              "engine output matches the framework reference")
        eng.stop()

        # -- 2. server / client / hot-swap / overload --------------------
        srv = ServingServer()
        addr = srv.serve()
        cli = ServingClient(addr)
        try:
            cli.load_model("m", d1, buckets=[1, 2, 4], max_wait_ms=1.0)
            h = cli.health()
            check(h.get("ok") and "m" in h.get("models", []),
                  "health reports the loaded model")
            out, v = cli.infer("m", {"x": probe})
            check(v == 1 and np.allclose(out[0], ref1, atol=1e-5),
                  "RPC infer serves v1")
            cli.load_model("m", d2, buckets=[1, 2, 4], max_wait_ms=1.0)
            out, v = cli.infer("m", {"x": probe})
            check(v == 2 and np.allclose(out[0], ref2, atol=1e-5),
                  "hot-swap flipped to v2 atomically")
            listed = cli.list_models()
            check(listed.get("m", {}).get("version") == 2,
                  "list_models shows the new version")

            # overload: tighten the admission bound, park the scheduler
            # on its batching timer (long enough that a contended host
            # still lands the flood inside the window), and flood —
            # extras must be refused IMMEDIATELY with ServerOverloaded,
            # not queued forever
            cli.load_model("m", d2, version=3, buckets=[1, 2, 4],
                           max_queue=1, max_wait_ms=1200.0)
            ok_n = over_n = 0

            def fire(i):
                nonlocal ok_n, over_n
                try:
                    cli2 = ServingClient(addr)
                    try:
                        cli2.infer("m", {"x": probe[:1]},
                                   deadline_ms=30000.0)
                        ok_n += 1
                    finally:
                        cli2.close()
                except ServerOverloaded:
                    over_n += 1

            with ThreadPoolExecutor(max_workers=8) as pool:
                list(pool.map(fire, range(8)))
            check(over_n > 0 and ok_n > 0,
                  f"overload sheds load ({ok_n} served, {over_n} refused)")
            check(_metrics.counter("serving.overloads").value() >= over_n,
                  "serving.overloads counted the rejections")
        finally:
            cli.close()
            srv.shutdown()

        # -- 3. decode: paged KV + continuous batching (ISSUE 6) ---------
        from . import DecodeEngine, DecoderSpec

        spec = DecoderSpec(vocab=32, d_model=16, n_layers=1, n_heads=2,
                           n_kv_heads=1, seed=3)
        deng = DecodeEngine(spec, name="dec", slots=[1, 2], page_size=4,
                            num_pages=24, max_seq_len=8)
        try:
            n_shapes = (len(deng.slot_ladder)
                        * len(deng.table_width_ladder)
                        * len(deng.chunk_ladder))
            check(len(deng.stats()["compiled_shapes"]) == n_shapes,
                  f"decode warm compiled the full ladder ({n_shapes} "
                  "shapes)")
            dc = _metrics.counter("serving.decode.compiles")
            base = dc.value()
            rng = np.random.RandomState(0)
            reqs = [deng.submit(
                rng.randint(0, 32, size=1 + int(rng.randint(4))),
                max_new_tokens=1 + int(rng.randint(4)))
                for _ in range(8)]
            ok = all(r.ev.wait(120) and r.error is None for r in reqs)
            check(ok, "ragged sequence churn all completed")
            check(dc.value() == base,
                  "churn performed 0 new decode compiles")
            check(deng.cache.allocator.stats()["pages_used"] == 0,
                  "every KV page returned to the pool")
            a = deng.generate([1, 2, 3], max_new_tokens=4)
            b = deng.generate([1, 2, 3], max_new_tokens=4)
            check(a["tokens"] == b["tokens"], "greedy decode deterministic")
            try:
                held = deng.cache.allocator.alloc(9999, 92)  # drain pool
                deng.submit([1, 2, 3, 4], max_new_tokens=4)
                check(False, "page exhaustion refused")
            except ServerOverloaded:
                check(True, "page exhaustion refused (ServerOverloaded)")
                deng.cache.allocator.free(9999)
        finally:
            deng.stop()

        # -- 4. chunked prefill (ISSUE 10): token-budget mixed steps ----
        ceng = DecodeEngine(spec, name="chunked", slots=[2], page_size=4,
                            num_pages=24, max_seq_len=20,
                            prefill_chunk=4)
        try:
            steps = _metrics.counter("serving.decode.steps")
            base = steps.value()
            prompt = list(range(12))
            out = ceng.generate(prompt, max_new_tokens=3)
            # steps-to-first-token bound: ceil(12/4) = 3, not 12
            check(out["steps_to_first_token"] == 3,
                  f"12-token prompt prefilled in "
                  f"{out['steps_to_first_token']} steps (== ceil(12/4))")
            check(steps.value() - base == 3 + 2,
                  "total steps = ceil(P/chunk) + (new - 1)")
            # mixed step: a decoding sequence co-rides a fresh prompt's
            # prefill chunks and never stalls behind them
            a = ceng.submit([5], max_new_tokens=6)
            b = ceng.submit(prompt, max_new_tokens=2)
            ok = a.ev.wait(120) and b.ev.wait(120) and \
                a.error is None and b.error is None
            check(ok and len(a.result["tokens"]) == 6
                  and len(b.result["tokens"]) == 2,
                  "mixed prefill+decode step completed both sequences")
            check(_metrics.counter(
                      "serving.decode.prefill_tokens").value() > 0,
                  "prefill token budget accounted "
                  "(serving.decode.prefill_tokens)")
            # chunking is engine-internal: greedy tokens identical with
            # chunking off (the PR 6 one-token-per-step behavior)
            ueng = DecodeEngine(spec, name="unchunked", slots=[2],
                                page_size=4, num_pages=24,
                                max_seq_len=20, prefill_chunk=1)
            try:
                u = ueng.generate(prompt, max_new_tokens=3)
                check(u["tokens"] == out["tokens"]
                      and u["steps_to_first_token"] == 12,
                      "greedy tokens identical with chunking on vs off "
                      "(12 steps unchunked, 3 chunked)")
            finally:
                ueng.stop()
        finally:
            ceng.stop()

        # -- 5. prefix caching + preemption (ISSUE 13) -------------------
        peng = DecodeEngine(spec, name="prefix", slots=[2], page_size=4,
                            num_pages=24, max_seq_len=20,
                            prefill_chunk=4, prefix_cache=True)
        try:
            prompt12 = list(range(12))
            cold = peng.generate(prompt12, max_new_tokens=3)
            check(cold["cached_tokens"] == 0
                  and cold["steps_to_first_token"] == 3,
                  "cold prompt prefilled in ceil(12/4) steps")
            # shared 8-token prefix, fresh suffix: prefill = the suffix
            warm = peng.generate(prompt12[:8] + [20, 21, 22, 23],
                                 max_new_tokens=3)
            check(warm["cached_tokens"] >= 8
                  and warm["steps_to_first_token"] == 1,
                  f"shared-prefix request mapped "
                  f"{warm['cached_tokens']} cached tokens, "
                  "first token in ceil(suffix/4) = 1 step")
            st = peng.cache.allocator.stats()
            check(st["pages_used"] == 0 and st["prefix_pages"] > 0,
                  "freed shared pages retained reclaimable "
                  f"({st['prefix_pages']} cached, 0 live)")
            cold2 = DecodeEngine(spec, name="prefix_cold", slots=[2],
                                 page_size=4, num_pages=24,
                                 max_seq_len=20, prefill_chunk=4,
                                 prefix_cache=False)
            try:
                ref = cold2.generate(prompt12[:8] + [20, 21, 22, 23],
                                     max_new_tokens=3)
                check(ref["tokens"] == warm["tokens"],
                      "cache-hit tokens identical to a cold engine's")
            finally:
                cold2.stop()
        finally:
            peng.stop()
        # demand reservation + preempt/restore: a pool far too small
        # for the worst case still completes everything, tokens equal
        # the unpreempted reference
        preempts = _metrics.counter("serving.kv.preemptions")
        base_pre = preempts.value()
        deng2 = DecodeEngine(spec, name="demand", slots=[4], page_size=4,
                             num_pages=13, max_seq_len=44,
                             prefill_chunk=4, prefix_cache=False,
                             reservation="demand")
        try:
            reqs = [deng2.submit([1 + i], max_new_tokens=30)
                    for i in range(4)]
            ok = all(r.ev.wait(240) and r.error is None for r in reqs)
            check(ok and preempts.value() > base_pre,
                  f"undersized pool completed via preempt+restore "
                  f"({preempts.value() - base_pre} preemptions)")
            check(deng2.cache.allocator.stats()["pages_used"] == 0,
                  "every page (spilled included) returned to the pool")
            wide = DecodeEngine(spec, name="demand_ref", slots=[4],
                                page_size=4, num_pages=64,
                                max_seq_len=44, prefill_chunk=4,
                                prefix_cache=False,
                                reservation="worst_case")
            try:
                sample = wide.generate([1], max_new_tokens=30)
                check(sample["tokens"] == reqs[0].result["tokens"],
                      "preempted tokens bitwise equal unpreempted "
                      "reference")
            finally:
                wide.stop()
        finally:
            deng2.stop()

        # -- 6. speculative decoding (ISSUE 14) --------------------------
        sspec = DecoderSpec(vocab=32, d_model=16, n_layers=1, n_heads=2,
                            n_kv_heads=1, seed=3)
        sdraft = DecoderSpec(vocab=32, d_model=8, n_layers=1, n_heads=1,
                             n_kv_heads=1, seed=3)
        ts = _metrics.counter("serving.decode.target_steps")
        s_off = DecodeEngine(sspec, name="spec_off", slots=[1],
                             page_size=4, num_pages=16, max_seq_len=20,
                             prefill_chunk=4)
        try:
            base = ts.value()
            ref = s_off.generate([4, 9, 1], max_new_tokens=12)
            off_steps = ts.value() - base
        finally:
            s_off.stop()
        dc = _metrics.counter("serving.decode.compiles")
        s_on = DecodeEngine(sspec, name="spec_on", slots=[1],
                            page_size=4, num_pages=16, max_seq_len=20,
                            prefill_chunk=4, draft_spec=sdraft,
                            spec_k=3)
        try:
            base_c = dc.value()
            base = ts.value()
            out = s_on.generate([4, 9, 1], max_new_tokens=12)
            on_steps = ts.value() - base
            check(out["tokens"] == ref["tokens"],
                  "speculative tokens bitwise equal non-speculative "
                  "(greedy)")
            check(on_steps < off_steps,
                  f"speculation used fewer target steps "
                  f"({on_steps} < {off_steps})")
            check(out["spec_proposed"] > 0
                  and out["accept_rate"] is not None,
                  f"accept_rate reported "
                  f"({out['accept_rate']}, {out['spec_proposed']} "
                  "proposed)")
            # before the fresh off-engine below warms ITS ladder into
            # the same process-global counter
            check(dc.value() == base_c,
                  "speculative rounds performed 0 post-warm compiles")
            s_off2 = DecodeEngine(sspec, name="spec_off2", slots=[1],
                                  page_size=4, num_pages=16,
                                  max_seq_len=20, prefill_chunk=4)
            try:
                a = s_off2.generate([7, 2], max_new_tokens=10,
                                    temperature=0.9, top_k=8, seed=11)
                b = s_on.generate([7, 2], max_new_tokens=10,
                                  temperature=0.9, top_k=8, seed=11)
                check(a["tokens"] == b["tokens"],
                      "seeded-sampled tokens identical with "
                      "speculation on vs off")
            finally:
                s_off2.stop()
            check(s_on.cache.allocator.stats()["pages_used"] == 0,
                  "rejected-suffix rollback returned every page")
        finally:
            s_on.stop()

        # -- 7. typed workloads (ISSUE 20) -------------------------------
        from .workloads import TokenMaskSpec, parse_workload, run_workload

        weng = DecodeEngine(spec, name="workloads", slots=[1, 2],
                            page_size=4, num_pages=64, max_seq_len=32,
                            prefill_chunk=4, prefix_cache=True,
                            embeddings=True)
        try:
            wl_shapes = len(weng.stats()["compiled_shapes"])
            # constrained decode: output in the mask's language, ends
            # when the automaton exhausts
            mask = TokenMaskSpec.regex("5 ( 7 | 9 ) 11")
            c1 = weng.generate([1, 2], max_new_tokens=8, mask=mask)
            check(len(c1["tokens"]) == 3 and c1["tokens"][0] == 5
                  and c1["tokens"][1] in (7, 9) and c1["tokens"][2] == 11,
                  f"constrained decode stayed in the mask language "
                  f"({c1['tokens']})")
            # batch-composition independence: same (seed, mask, prompt)
            # under concurrent load, bitwise-identical tokens
            cs1 = weng.generate([1, 2], max_new_tokens=8,
                                mask=mask.to_dict(), temperature=0.9,
                                top_k=8, seed=5)
            bg = [weng.submit([9, 9, int(i)], max_new_tokens=6)
                  for i in range(3)]
            cs2 = weng.generate([1, 2], max_new_tokens=8,
                                mask=mask.to_dict(), temperature=0.9,
                                top_k=8, seed=5)
            check(all(r.ev.wait(120) for r in bg)
                  and cs2["tokens"] == cs1["tokens"],
                  "constrained sampling batch-composition-independent "
                  "(idle == loaded, bitwise)")
            # embeddings: zero decode slots consumed
            dreq = _metrics.counter("serving.decode.requests")
            base_dreq = dreq.value()
            embeds = [weng.submit_embed(list(range(2 + i)))
                      for i in range(4)]
            ok = all(e.ev.wait(120) and e.error is None for e in embeds)
            live_g = _metrics.gauge(
                "serving.decode.live_slots.workloads.v1")
            check(ok and all(
                len(e.result["embedding"]) == spec.d_model
                and len(e.result["logprobs"]) == len(e.prompt) - 1
                for e in embeds),
                "embeddings pooled d_model dims + per-token logprobs")
            check(dreq.value() == base_dreq and live_g.value() == 0,
                  "embeddings completed with decode live_slots "
                  "untouched (zero slots, zero decode requests)")
            # beam: page sharing proven by counters, tokens by equality
            bres = run_workload(weng, {
                "kind": "beam", "prompt": [3, 1, 4, 1, 5, 9, 2, 6],
                "k": 3, "max_new_tokens": 5})
            check(bres["kind"] == "beam" and len(bres["beams"]) == 3
                  and bres["shared_prompt_pages"] > 0
                  and all(c > 0 for c in bres["cached_tokens"]),
                  f"beam children shared prompt pages "
                  f"({bres['shared_prompt_pages']} refcounted, "
                  f"{bres['cached_tokens']} cached tokens)")
            inds = [weng.generate([3, 1, 4, 1, 5, 9, 2, 6, b[0]],
                                  max_new_tokens=4)["tokens"]
                    for b in bres["beams"]]
            check(all(b[1:] == ind
                      for b, ind in zip(bres["beams"], inds)),
                  "temp-0 beams bitwise equal independent decodes")
            # dispatch layer: unknown kinds refuse before any engine work
            try:
                parse_workload({"kind": "nope", "prompt": [1]})
                check(False, "unknown workload kind refused")
            except ValueError:
                check(True, "unknown workload kind refused (ValueError)")
            check(len(weng.stats()["compiled_shapes"]) == wl_shapes,
                  "workload mix performed 0 post-warm compiles")
            check(weng.cache.allocator.stats()["pages_used"] == 0,
                  "workload mix returned every KV page")
        finally:
            weng.stop()

        # decode over RPC with a hot-swap
        srv2 = ServingServer()
        addr2 = srv2.serve()
        cli2 = ServingClient(addr2)
        try:
            cli2.load_decoder("dec", spec.to_dict(), slots=[1, 2],
                              page_size=4, num_pages=16, max_seq_len=8)
            out = cli2.generate("dec", [3, 1], max_new_tokens=4)
            check(out["version"] == 1 and len(out["tokens"]) == 4,
                  "RPC generate serves the decoder")
            cli2.load_decoder("dec", spec.to_dict(), slots=[1, 2],
                              page_size=4, num_pages=16, max_seq_len=8)
            out2 = cli2.generate("dec", [3, 1], max_new_tokens=4)
            check(out2["version"] == 2 and out2["tokens"] == out["tokens"],
                  "decoder hot-swap flipped with identical tokens")
            # streaming generate (ISSUE 12): same tokens, incrementally
            s = cli2.generate("dec", [3, 1], max_new_tokens=4,
                              stream=True)
            check(list(s) == out["tokens"]
                  and s.result["prompt_len"] == 2,
                  "streamed tokens equal buffered (greedy)")
            # checkpoint deploy (ISSUE 12): save the spec'd decoder,
            # redeploy from the manifest, tokens bitwise identical
            from paddle_tpu.checkpoint import save_decoder_checkpoint

            ckdir = os.path.join(tmp, "dec_ck")
            save_decoder_checkpoint(ckdir, spec)
            cli2.load_decoder("dec_ck", checkpoint_dir=ckdir,
                              slots=[1, 2], page_size=4, num_pages=16,
                              max_seq_len=8)
            out3 = cli2.generate("dec_ck", [3, 1], max_new_tokens=4)
            check(out3["tokens"] == out["tokens"],
                  "checkpoint_dir deploy serves bitwise the same model")
            # typed workloads over RPC (ISSUE 20): one "workload"
            # method, kind-dispatched server-side
            cli2.load_decoder("wl", spec.to_dict(), slots=[1, 2],
                              page_size=4, num_pages=32, max_seq_len=16,
                              prefix_cache=True, embeddings=True)
            from .workloads import TokenMaskSpec as _TMS

            wc = cli2.constrained("wl", [1, 2],
                                  _TMS.regex("5 ( 7 | 9 ) 11"),
                                  max_new_tokens=6)
            check(wc["kind"] == "constrained"
                  and wc["tokens"][0] == 5 and wc["tokens"][-1] == 11,
                  "RPC constrained workload decoded in-language")
            we = cli2.embed("wl", [1, 2, 3, 4])
            check(len(we["embedding"]) == spec.d_model
                  and len(we["logprobs"]) == 3,
                  "RPC embed workload returned pooled states + "
                  "logprobs")
            wb = cli2.beam("wl", [3, 1, 4, 1, 5, 9], k=2,
                           max_new_tokens=4)
            check(len(wb["beams"]) == 2
                  and wb["shared_prompt_pages"] > 0,
                  "RPC beam workload shared prompt pages")
        finally:
            cli2.close()
            srv2.shutdown()

    if failures:
        print(f"serving selftest: {len(failures)} FAILURE(S): {failures}")
        return 1
    print("serving selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.serving")
    ap.add_argument("--selftest", action="store_true",
                    help="run the in-process end-to-end selftest")
    ap.add_argument("--serve", action="store_true",
                    help="start a ServingServer")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--load", action="append", default=[],
                    metavar="NAME=DIR",
                    help="model(s) to load at startup (repeatable)")
    args = ap.parse_args(argv)

    _force_cpu()
    if args.serve:
        from . import InferenceEngine, ServingServer

        srv = ServingServer()
        host, port = srv.serve(args.host, args.port)
        for spec in args.load:
            name, _, dirname = spec.partition("=")
            if not dirname:
                print(f"bad --load {spec!r} (want NAME=DIR)")
                return 2
            eng = srv.registry.deploy(
                name,
                lambda d=dirname, n=name:
                    InferenceEngine.from_inference_dir(d, name=n))
            print(f"loaded {name} v{eng.version} from {dirname}")
        print(f"serving on {host}:{port} (ctrl-c to stop)")
        try:
            import time

            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            srv.shutdown()
        return 0
    # default: selftest
    return run_selftest()


if __name__ == "__main__":
    sys.exit(main())
