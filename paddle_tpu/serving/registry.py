"""Versioned model registry with atomic hot-swap.

The contract a serving fleet needs from "deploy a new version":

  1. The new version is loaded AND warmed (one compile per bucket-ladder
     entry) in the background, while the old version keeps serving.
  2. The name -> engine pointer flips atomically under the registry
     lock: after the flip every `get()` returns the new engine.
  3. The old engine is then retired with `stop(drain=True)` — it
     completes every request already admitted, so a swap drops ZERO
     in-flight requests. Requests that raced the flip and landed on the
     retiring engine get EngineRetired, which the server resubmits to
     the current engine (serving.swap_resubmits counts those).
  4. A failed load/warm raises BEFORE the flip: the registry is
     untouched and the old version keeps serving — rollback is the
     default, not a recovery procedure.
  5. After retirement the engine releases its Program/Scope/Executor, so
     the executor's WeakKeyDictionary jit cache frees the old version's
     compiled executables — many version flips must not accumulate
     compile-cache residue (weakref-regression-tested).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ..observability import metrics as _metrics
from ..observability.log import get_logger
from .engine import InferenceEngine
from .errors import ModelNotFound

__all__ = ["ModelRegistry"]

_log = get_logger("serving")

_m_loads = _metrics.counter("serving.model_loads")
_m_unloads = _metrics.counter("serving.model_unloads")
_m_swaps = _metrics.counter("serving.hot_swaps")


class ModelRegistry:
    """name -> live engine, with swap/unload lifecycle.

    Engine-kind-agnostic: anything with ``name``/``version``/``kind``/
    ``stats()``/``stop(drain=)`` deploys here — the one-shot
    InferenceEngine and the decode DecodeEngine share the registry (and
    therefore the hot-swap drain + executable-release guarantees)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._models: Dict[str, InferenceEngine] = {}  # guarded-by: _mu

    def deploy(self, name: str,
               build: Callable[[], InferenceEngine]) -> InferenceEngine:
        """Load (`build` returns a WARMED engine, or raises) then flip.
        The expensive part — load + one compile per bucket — happens
        before the lock is ever taken, so serving never stalls on a
        deploy, and a build failure leaves the old version installed
        (rollback by construction)."""
        engine = build()
        try:
            with self._mu:
                old = self._models.get(name)
                self._models[name] = engine
        except BaseException:  # pragma: no cover - only on interpreter death
            engine.stop(drain=False)
            raise
        _m_loads.inc()
        if old is not None:
            _m_swaps.inc()
            _log.info("hot-swap %s: v%d -> v%d (draining old)",
                      name, old.version, engine.version)
            # outside the lock: draining can take a full batch turn, and
            # get() must already be answering with the new engine
            old.stop(drain=True)
        return engine

    def get(self, name: str) -> InferenceEngine:
        with self._mu:
            eng = self._models.get(name)
        if eng is None:
            raise ModelNotFound(
                f"no model registered under '{name}' "
                f"(loaded: {sorted(self.names())})")
        return eng

    def names(self) -> List[str]:
        with self._mu:
            return sorted(self._models)

    def unload(self, name: str, drain: bool = True) -> Dict[str, Any]:
        with self._mu:
            eng = self._models.pop(name, None)
        if eng is None:
            raise ModelNotFound(f"no model registered under '{name}'")
        eng.stop(drain=drain)
        info = eng.stats()  # AFTER the drain: truly final numbers
        _m_unloads.inc()
        return info

    def unload_all(self, drain: bool = True):
        for name in self.names():
            try:
                self.unload(name, drain=drain)
            except ModelNotFound:  # raced another unload
                pass

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            engines = dict(self._models)
        return {name: eng.stats() for name, eng in sorted(engines.items())}
