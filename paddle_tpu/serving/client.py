"""ServingClient — typed client over distributed/rpc.py's RpcClient.

Transport retries are SAFE by construction: every frame carries the
idempotency token, and the server routes `infer` through its dedup
cache, so a retransmit after a dropped reply is answered from the
original response without re-running the batch. Application errors come
back as ``"<TypeName>: <message>"`` strings; `_raise_typed` maps the
name back to the serving exception class (ServerOverloaded,
DeadlineExceeded, ...) so callers catch types, not regexes."""
from __future__ import annotations

import re
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..distributed.rpc import RpcClient
from .errors import (DeadlineExceeded, EngineRetired, ModelNotFound,
                     RequestTooLarge, ServerOverloaded, ServingError,
                     StreamExpired)

__all__ = ["ServingClient", "TokenStream"]

from ..checkpoint.format import CheckpointCorruptError, CheckpointError

_TYPED = {cls.__name__: cls for cls in
          (ServerOverloaded, DeadlineExceeded, ModelNotFound,
           RequestTooLarge, EngineRetired, ServingError, StreamExpired,
           # checkpoint deploy refusals arrive typed (a corrupt segment
           # keeps its tensor-named message across the wire)
           CheckpointError, CheckpointCorruptError,
           ValueError)}  # ValueError: spec/feed validation refusals

# rpc.py's client raises RuntimeError("RPC <m> failed: <Type>: <msg>")
_ERR_RE = re.compile(r"^RPC \S+ failed: (\w+): (.*)$", re.DOTALL)


def _ladder_arg(v):
    """Bucket/slot ladders ride the wire as int lists — except the
    literal string 'auto', which must reach the SERVER intact so the
    ladder resolves against the server's device kind, observed traffic,
    and tuning cache (autotune), not the client's."""
    if v is None or (isinstance(v, str) and v.strip().lower() == "auto"):
        return v
    return [int(x) for x in v]


def _raise_typed(e: RuntimeError):
    m = _ERR_RE.match(str(e))
    if m and m.group(1) in _TYPED:
        raise _TYPED[m.group(1)](m.group(2)) from e
    raise


class TokenStream:
    """Iterator over one streaming generate (ISSUE 12): yields tokens
    as the server decodes them, pulling chunked continuation frames
    over the framed RPC. The CLIENT owns the cursor (every frame names
    its offset explicitly), so a retransmitted frame after a lost reply
    is answered token-exact — and a fleet router can resume the same
    cursor on a different replica after a failover.

    ``delivered`` counts tokens handed to the caller; after exhaustion
    ``result`` holds the final dict (tokens / prompt_len / version /
    steps_to_first_token). ``close()`` (idempotent, best-effort) tells
    the server to cancel an unfinished sequence; iterating to the end
    closes automatically. Typed serving errors (DeadlineExceeded, ...)
    raise out of iteration; transport failures raise ConnectionError —
    the router's failover signal."""

    def __init__(self, cli: "ServingClient", model: str,
                 header: Dict[str, Any], wait_ms: float = 20000.0):
        self._cli = cli
        self._id = str(header["stream"])
        self._wait_ms = float(wait_ms)
        self._pending: deque = deque()
        self._next_offset = 0
        self._done = False
        self._closed = False
        self.model = str(model)
        self.version = int(header["version"])
        self.prompt_len = int(header["prompt_len"])
        self.delivered = 0
        self.result: Optional[Dict[str, Any]] = None

    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> int:
        while not self._pending and not self._done:
            try:
                resp = self._cli._stream_next(
                    self._id, self._next_offset, self._wait_ms)
            except StreamExpired:
                # the server already dropped the stream — nothing left
                # to close
                self._closed = True
                raise
            except ServingError:
                # terminal typed failure (DeadlineExceeded, retirement,
                # ...): release the server-side stream slot NOW instead
                # of leaving it to the idle-TTL sweep — a burst of
                # failed streams must not pin the bounded table
                self.close()
                raise
            self._pending.extend(int(t) for t in resp["tokens"])
            self._next_offset = int(resp["next_offset"])
            if resp.get("done"):
                self._done = True
                self.result = resp.get("result")
        if self._pending:
            self.delivered += 1
            return self._pending.popleft()
        self.close()
        raise StopIteration

    def close(self):
        """Release the server-side stream (cancels an unfinished
        sequence). Best-effort: a dead server's stream dies with it."""
        if self._closed:
            return
        self._closed = True
        try:
            self._cli._stream_close(self._id)
        except (ConnectionError, OSError, ServingError):
            pass

    def __enter__(self) -> "TokenStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ServingClient:
    """Blocking client for one ServingServer endpoint."""

    def __init__(self, addr, timeout: float = 180.0, retries: int = 3):
        self._rpc = RpcClient(addr, timeout=timeout, retries=retries)

    def infer(self, model: str, feeds: Dict[str, Any],
              deadline_ms: Optional[float] = None
              ) -> Tuple[List[np.ndarray], int]:
        """Returns (outputs, served_version). Raises ServerOverloaded /
        DeadlineExceeded / ModelNotFound / RequestTooLarge."""
        wire_feeds = {str(k): np.asarray(v) for k, v in feeds.items()}
        try:
            resp = self._rpc.call("infer", model, wire_feeds, deadline_ms)
        except RuntimeError as e:
            _raise_typed(e)
        return ([np.asarray(o) for o in resp["outputs"]],
                int(resp["version"]))

    def generate(self, model: str, prompt: Sequence[int],
                 max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, stream: bool = False,
                 stream_wait_ms: float = 20000.0
                 ) -> Union[Dict[str, Any], TokenStream]:
        """Autoregressive decode on a loaded decoder. Buffered
        (default) returns ``{"model", "version", "tokens",
        "prompt_len"}`` when the whole sequence finishes;
        ``stream=True`` returns a ``TokenStream`` that yields tokens AS
        THEY DECODE — the first one ~ceil(prompt/prefill_chunk) decode
        steps after admission instead of after the last token (the
        chunked-prefill win, finally visible to a client). Transport
        retries are dedup-safe either way: a retransmitted generate (or
        stream frame) is answered from the server's cache without
        re-decoding. ``temperature``/``top_k``/``seed`` select the
        per-request sampling policy (0.0 = greedy argmax; sampled
        output is deterministic given the seed — which is also what
        makes a fleet-level stream resume exact)."""
        prompt = [int(t) for t in prompt]
        try:
            if stream:
                header = self._rpc.call(
                    "generate_stream_start", model, prompt,
                    int(max_new_tokens), deadline_ms, float(temperature),
                    int(top_k), int(seed))
                return TokenStream(self, model, header,
                                   wait_ms=stream_wait_ms)
            return self._rpc.call(
                "generate", model, prompt,
                int(max_new_tokens), deadline_ms, float(temperature),
                int(top_k), int(seed))
        except RuntimeError as e:
            _raise_typed(e)

    def _stream_next(self, stream_id: str, offset: int,
                     wait_ms: float) -> Dict[str, Any]:
        try:
            return self._rpc.call("generate_stream_next", stream_id,
                                  int(offset), float(wait_ms))
        except RuntimeError as e:
            _raise_typed(e)

    def _stream_close(self, stream_id: str) -> Dict[str, Any]:
        try:
            return self._rpc.call("generate_stream_close", stream_id)
        except RuntimeError as e:
            _raise_typed(e)

    # -- typed workloads (ISSUE 20) ---------------------------------------
    def workload(self, model: str, workload: Dict[str, Any]
                 ) -> Dict[str, Any]:
        """Run one typed workload — a dict with a ``kind`` field
        ('generate' | 'constrained' | 'embed' | 'beam'; see
        serving.workloads.parse_workload for each kind's fields) — on a
        loaded decoder. Unknown kinds/fields refuse server-side before
        any engine work. Transport retries are dedup-safe: a
        retransmitted workload (beam included) is answered from the
        server's reply cache, never re-decoded."""
        try:
            return self._rpc.call("workload", model, dict(workload))
        except RuntimeError as e:
            _raise_typed(e)

    def constrained(self, model: str, prompt: Sequence[int], mask: Any,
                    max_new_tokens: int = 16,
                    deadline_ms: Optional[float] = None,
                    temperature: float = 0.0, top_k: int = 0,
                    seed: int = 0) -> Dict[str, Any]:
        """Grammar-constrained decode: ``mask`` is a TokenMaskSpec or
        its wire dict; disallowed tokens are masked from the logits
        before the per-(seed, position) choice, so output is exactly as
        deterministic as unconstrained generate."""
        if hasattr(mask, "to_dict"):
            mask = mask.to_dict()
        return self.workload(model, {
            "kind": "constrained", "prompt": [int(t) for t in prompt],
            "mask": dict(mask), "max_new_tokens": int(max_new_tokens),
            "deadline_ms": deadline_ms,
            "temperature": float(temperature), "top_k": int(top_k),
            "seed": int(seed)})

    def embed(self, model: str, prompt: Sequence[int],
              deadline_ms: Optional[float] = None) -> Dict[str, Any]:
        """Prompt-only embedding/scoring: mean-pooled final hidden
        state + per-token logprobs, served from the decoder's embed
        lane (load it with ``embeddings=True``) without occupying any
        decode slot."""
        return self.workload(model, {
            "kind": "embed", "prompt": [int(t) for t in prompt],
            "deadline_ms": deadline_ms})

    def beam(self, model: str, prompt: Sequence[int], k: int = 2,
             max_new_tokens: int = 16,
             deadline_ms: Optional[float] = None) -> Dict[str, Any]:
        """n-best decode: the k best single-token forks, each decoded
        greedily to ``max_new_tokens``, sharing the prompt's KV pages
        via the server decoder's prefix index (load with
        ``prefix_cache=True``)."""
        return self.workload(model, {
            "kind": "beam", "prompt": [int(t) for t in prompt],
            "k": int(k), "max_new_tokens": int(max_new_tokens),
            "deadline_ms": deadline_ms})

    def load_decoder(self, model: str,
                     spec: Optional[Dict[str, Any]] = None,
                     version: Optional[int] = None,
                     slots: Optional[Sequence[int]] = None,
                     page_size: Optional[int] = None,
                     num_pages: Optional[int] = None,
                     max_seq_len: Optional[int] = None,
                     max_queue: Optional[int] = None,
                     prefill_chunk: Optional[int] = None,
                     checkpoint_dir: Optional[str] = None,
                     prefix_cache: Optional[bool] = None,
                     reservation: Optional[str] = None,
                     draft_spec: Optional[Dict[str, Any]] = None,
                     draft_checkpoint_dir: Optional[str] = None,
                     spec_k: Optional[int] = None,
                     mesh_axes: Optional[str] = None,
                     embeddings: bool = False
                     ) -> Dict[str, Any]:
        """Deploy a DecodeEngine; hot-swaps like load_model. From a
        ``spec`` dict (see serving.decode.DecoderSpec) the server
        builds the deterministic seed decoder; ``checkpoint_dir`` (a
        path on the SERVER's filesystem) deploys real weights from a
        manifest checkpoint — spec optional then, and if given it must
        match the checkpoint's. ``prefill_chunk`` pins the chunked-
        prefill token budget (None = the server resolves it through its
        autotune cache/FLAGS). ``prefix_cache``/``reservation`` pin the
        ISSUE 13 policies (prompt-prefix KV reuse; 'demand' vs
        'worst_case' page reservation) — None defers to the server's
        FLAGS. ``draft_spec``/``draft_checkpoint_dir``/``spec_k``
        attach a speculative draft decoder (ISSUE 14: the draft
        proposes spec_k tokens per slot per round, the target verifies
        them in one chunked step; output stays bitwise-equal to
        non-speculative decode). spec_k=None defers to the server's
        autotune cache/FLAGS; a vocab/eos-mismatched draft is refused
        typed at load. ``mesh_axes`` (ISSUE 15, e.g. "tp=2") makes the
        replica SPAN chips — params shard per the decoder rules and the
        paged KV pool shards over the kv-head axis; '' pins single-chip
        even when the checkpoint recorded a mesh, None defers to the
        checkpoint's recording, then the server's FLAGS.
        ``embeddings=True`` (ISSUE 20) warms the embed lane's compiled
        shapes so the decoder also serves prompt-only
        embedding/scoring workloads."""
        try:
            return self._rpc.call(
                "load_decoder", model,
                None if spec is None else dict(spec), version,
                _ladder_arg(slots),
                page_size, num_pages, max_seq_len, max_queue,
                None if prefill_chunk is None else int(prefill_chunk),
                None if checkpoint_dir is None else str(checkpoint_dir),
                None if prefix_cache is None else bool(prefix_cache),
                None if reservation is None else str(reservation),
                None if draft_spec is None else dict(draft_spec),
                (None if draft_checkpoint_dir is None
                 else str(draft_checkpoint_dir)),
                None if spec_k is None else int(spec_k),
                None if mesh_axes is None else str(mesh_axes),
                bool(embeddings))
        except RuntimeError as e:
            _raise_typed(e)

    def load_model(self, model: str, dirname: str,
                   version: Optional[int] = None, kind: str = "auto",
                   buckets: Optional[Sequence[int]] = None,
                   max_queue: Optional[int] = None,
                   max_wait_ms: Optional[float] = None) -> Dict[str, Any]:
        try:
            return self._rpc.call("load_model", model, dirname, version,
                                  kind, _ladder_arg(buckets),
                                  max_queue, max_wait_ms)
        except RuntimeError as e:
            _raise_typed(e)

    def unload_model(self, model: str) -> Dict[str, Any]:
        try:
            return self._rpc.call("unload_model", model)
        except RuntimeError as e:
            _raise_typed(e)

    def list_models(self) -> Dict[str, Any]:
        return self._rpc.call("list_models")

    def load_report(self) -> Dict[str, Any]:
        """Structured per-model load snapshot (free KV pages, live
        slots, queue depths, model/version set) — the routing signal;
        idempotent server-side, so scraping it never occupies
        dedup-cache slots."""
        return self._rpc.call("load_report")

    def health(self) -> Dict[str, Any]:
        return self._rpc.call("health")

    def close(self):
        self._rpc.close()
