"""ServingClient — typed client over distributed/rpc.py's RpcClient.

Transport retries are SAFE by construction: every frame carries the
idempotency token, and the server routes `infer` through its dedup
cache, so a retransmit after a dropped reply is answered from the
original response without re-running the batch. Application errors come
back as ``"<TypeName>: <message>"`` strings; `_raise_typed` maps the
name back to the serving exception class (ServerOverloaded,
DeadlineExceeded, ...) so callers catch types, not regexes."""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..distributed.rpc import RpcClient
from .errors import (DeadlineExceeded, EngineRetired, ModelNotFound,
                     RequestTooLarge, ServerOverloaded, ServingError)

__all__ = ["ServingClient"]

_TYPED = {cls.__name__: cls for cls in
          (ServerOverloaded, DeadlineExceeded, ModelNotFound,
           RequestTooLarge, EngineRetired, ServingError,
           ValueError)}  # ValueError: spec/feed validation refusals

# rpc.py's client raises RuntimeError("RPC <m> failed: <Type>: <msg>")
_ERR_RE = re.compile(r"^RPC \S+ failed: (\w+): (.*)$", re.DOTALL)


def _ladder_arg(v):
    """Bucket/slot ladders ride the wire as int lists — except the
    literal string 'auto', which must reach the SERVER intact so the
    ladder resolves against the server's device kind, observed traffic,
    and tuning cache (autotune), not the client's."""
    if v is None or (isinstance(v, str) and v.strip().lower() == "auto"):
        return v
    return [int(x) for x in v]


def _raise_typed(e: RuntimeError):
    m = _ERR_RE.match(str(e))
    if m and m.group(1) in _TYPED:
        raise _TYPED[m.group(1)](m.group(2)) from e
    raise


class ServingClient:
    """Blocking client for one ServingServer endpoint."""

    def __init__(self, addr, timeout: float = 180.0, retries: int = 3):
        self._rpc = RpcClient(addr, timeout=timeout, retries=retries)

    def infer(self, model: str, feeds: Dict[str, Any],
              deadline_ms: Optional[float] = None
              ) -> Tuple[List[np.ndarray], int]:
        """Returns (outputs, served_version). Raises ServerOverloaded /
        DeadlineExceeded / ModelNotFound / RequestTooLarge."""
        wire_feeds = {str(k): np.asarray(v) for k, v in feeds.items()}
        try:
            resp = self._rpc.call("infer", model, wire_feeds, deadline_ms)
        except RuntimeError as e:
            _raise_typed(e)
        return ([np.asarray(o) for o in resp["outputs"]],
                int(resp["version"]))

    def generate(self, model: str, prompt: Sequence[int],
                 max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0) -> Dict[str, Any]:
        """Autoregressive decode on a loaded decoder. Returns
        ``{"model", "version", "tokens", "prompt_len"}``. Transport
        retries are dedup-safe: a retransmitted generate is answered
        from the server's cache without re-decoding the sequence.
        ``temperature``/``top_k``/``seed`` select the per-request
        sampling policy (0.0 = greedy argmax; sampled output is
        deterministic given the seed)."""
        try:
            return self._rpc.call(
                "generate", model, [int(t) for t in prompt],
                int(max_new_tokens), deadline_ms, float(temperature),
                int(top_k), int(seed))
        except RuntimeError as e:
            _raise_typed(e)

    def load_decoder(self, model: str, spec: Dict[str, Any],
                     version: Optional[int] = None,
                     slots: Optional[Sequence[int]] = None,
                     page_size: Optional[int] = None,
                     num_pages: Optional[int] = None,
                     max_seq_len: Optional[int] = None,
                     max_queue: Optional[int] = None,
                     prefill_chunk: Optional[int] = None
                     ) -> Dict[str, Any]:
        """Deploy a DecodeEngine from an architecture/seed spec dict
        (see serving.decode.DecoderSpec); hot-swaps like load_model.
        ``prefill_chunk`` pins the chunked-prefill token budget (None =
        the server resolves it through its autotune cache/FLAGS)."""
        try:
            return self._rpc.call(
                "load_decoder", model, dict(spec), version,
                _ladder_arg(slots),
                page_size, num_pages, max_seq_len, max_queue,
                None if prefill_chunk is None else int(prefill_chunk))
        except RuntimeError as e:
            _raise_typed(e)

    def load_model(self, model: str, dirname: str,
                   version: Optional[int] = None, kind: str = "auto",
                   buckets: Optional[Sequence[int]] = None,
                   max_queue: Optional[int] = None,
                   max_wait_ms: Optional[float] = None) -> Dict[str, Any]:
        try:
            return self._rpc.call("load_model", model, dirname, version,
                                  kind, _ladder_arg(buckets),
                                  max_queue, max_wait_ms)
        except RuntimeError as e:
            _raise_typed(e)

    def unload_model(self, model: str) -> Dict[str, Any]:
        try:
            return self._rpc.call("unload_model", model)
        except RuntimeError as e:
            _raise_typed(e)

    def list_models(self) -> Dict[str, Any]:
        return self._rpc.call("list_models")

    def load_report(self) -> Dict[str, Any]:
        """Structured per-model load snapshot (free KV pages, live
        slots, queue depths, model/version set) — the routing signal;
        idempotent server-side, so scraping it never occupies
        dedup-cache slots."""
        return self._rpc.call("load_report")

    def health(self) -> Dict[str, Any]:
        return self._rpc.call("health")

    def close(self):
        self._rpc.close()
