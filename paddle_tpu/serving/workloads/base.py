"""Typed request classes the serving stack dispatches on (ISSUE 20).

One ``kind`` field on the wire selects the workload:

    generate     next-token generation (the pre-existing behavior)
    constrained  generation under a TokenMaskSpec (masks.py)
    embed        prompt-only pooled hidden states + per-token logprobs
    beam         n-best: k sibling continuations over SHARED prompt
                 pages (beam.py)

``parse_workload`` validates a wire dict into a workload object
(unknown kinds refuse loudly — a typo must not silently decode
unconstrained); ``run_workload`` executes one against a DecodeEngine
and carries the per-kind observability: a ``serving.workload.<kind>``
fault site (chaos seam), span, request counter, and latency histogram.
The dispatch lives HERE rather than in the server so the engine-direct
benches and the RPC path populate the same per-kind series.

Every workload runs on mechanism the engine already warms: constrained
decode is host-side logit masking over the plain step, embeddings ride
the chunked-prefill path in their own slot lane, and beams are prefix-
index sharing — a mixed churn of all four kinds performs zero
post-warm compiles (pinned by the selftest and the mixed bench).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from ...distributed import faults as _faults
from ...observability import metrics as _metrics, tracing as _tracing
from .masks import TokenMaskSpec

__all__ = ["Workload", "GenerateWorkload", "ConstrainedWorkload",
           "EmbedWorkload", "BeamWorkload", "WORKLOAD_KINDS",
           "parse_workload", "run_workload"]

WORKLOAD_KINDS = ("generate", "constrained", "embed", "beam")


def _prompt_of(d: Dict[str, Any]) -> List[int]:
    prompt = d.get("prompt")
    if not prompt:
        raise ValueError("workload needs a non-empty 'prompt'")
    return [int(t) for t in prompt]


class Workload:
    """Base class: ``kind`` + wire (de)serialization. Subclasses
    implement ``run(engine)`` returning the result dict."""

    kind = ""

    def to_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    def run(self, engine) -> Dict[str, Any]:
        raise NotImplementedError


class GenerateWorkload(Workload):
    kind = "generate"

    def __init__(self, prompt: Sequence[int], max_new_tokens: int = 16,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 deadline_ms: Optional[float] = None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.deadline_ms = deadline_ms

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "prompt": self.prompt,
                "max_new_tokens": self.max_new_tokens,
                "temperature": self.temperature, "top_k": self.top_k,
                "seed": self.seed, "deadline_ms": self.deadline_ms}

    def run(self, engine) -> Dict[str, Any]:
        return engine.generate(
            self.prompt, self.max_new_tokens,
            deadline_ms=self.deadline_ms, temperature=self.temperature,
            top_k=self.top_k, seed=self.seed)


class ConstrainedWorkload(GenerateWorkload):
    kind = "constrained"

    def __init__(self, prompt: Sequence[int], mask: Any,
                 max_new_tokens: int = 16, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0,
                 deadline_ms: Optional[float] = None):
        super().__init__(prompt, max_new_tokens, temperature, top_k,
                         seed, deadline_ms)
        if isinstance(mask, dict):
            mask = TokenMaskSpec.from_dict(mask)
        if not isinstance(mask, TokenMaskSpec):
            raise ValueError(
                f"constrained workload needs a TokenMaskSpec (or its "
                f"wire dict), got {type(mask).__name__}")
        self.mask = mask

    def to_dict(self) -> Dict[str, Any]:
        d = super().to_dict()
        d["kind"] = self.kind
        d["mask"] = self.mask.to_dict()
        return d

    def run(self, engine) -> Dict[str, Any]:
        return engine.generate(
            self.prompt, self.max_new_tokens,
            deadline_ms=self.deadline_ms, temperature=self.temperature,
            top_k=self.top_k, seed=self.seed, mask=self.mask)


class EmbedWorkload(Workload):
    kind = "embed"

    def __init__(self, prompt: Sequence[int],
                 deadline_ms: Optional[float] = None):
        self.prompt = [int(t) for t in prompt]
        self.deadline_ms = deadline_ms

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "prompt": self.prompt,
                "deadline_ms": self.deadline_ms}

    def run(self, engine) -> Dict[str, Any]:
        return engine.embed(self.prompt, deadline_ms=self.deadline_ms)


class BeamWorkload(Workload):
    kind = "beam"

    def __init__(self, prompt: Sequence[int], k: int = 2,
                 max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None):
        self.prompt = [int(t) for t in prompt]
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"beam width k must be >= 1, got {self.k}")
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_ms = deadline_ms

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "prompt": self.prompt, "k": self.k,
                "max_new_tokens": self.max_new_tokens,
                "deadline_ms": self.deadline_ms}

    def run(self, engine) -> Dict[str, Any]:
        from .beam import beam_search

        return beam_search(engine, self.prompt, self.k,
                           self.max_new_tokens,
                           deadline_ms=self.deadline_ms)


_KIND_ARGS = {
    "generate": ("max_new_tokens", "temperature", "top_k", "seed",
                 "deadline_ms"),
    "constrained": ("mask", "max_new_tokens", "temperature", "top_k",
                    "seed", "deadline_ms"),
    "embed": ("deadline_ms",),
    "beam": ("k", "max_new_tokens", "deadline_ms"),
}

_KIND_CLS = {
    "generate": GenerateWorkload,
    "constrained": ConstrainedWorkload,
    "embed": EmbedWorkload,
    "beam": BeamWorkload,
}


def parse_workload(wire: Dict[str, Any]) -> Workload:
    """Wire dict -> workload object. Refuses unknown kinds AND unknown
    keys: a misspelled field silently falling back to a default is a
    wrong-workload dispatch (same discipline as DecoderSpec.from_dict).
    """
    if isinstance(wire, Workload):
        return wire
    if not isinstance(wire, dict):
        raise ValueError(
            f"workload must be a dict, got {type(wire).__name__}")
    kind = wire.get("kind", "generate")
    cls = _KIND_CLS.get(kind)
    if cls is None:
        raise ValueError(
            f"unknown workload kind {kind!r}; valid: "
            f"{sorted(WORKLOAD_KINDS)}")
    allowed = set(_KIND_ARGS[kind]) | {"kind", "prompt"}
    unknown = sorted(set(wire) - allowed)
    if unknown:
        raise ValueError(
            f"workload kind {kind!r} has unknown field(s) {unknown}; "
            f"valid: {sorted(allowed)}")
    kwargs = {k: wire[k] for k in _KIND_ARGS[kind] if k in wire
              and wire[k] is not None}
    return cls(_prompt_of(wire), **kwargs)


def run_workload(engine, w: Any) -> Dict[str, Any]:
    """Execute one workload against a DecodeEngine with the per-kind
    observability envelope: ``serving.workload.<kind>`` is the chaos
    fault site AND the span name; ``.requests``/``.ms`` are the
    counter/latency series the mixed-workload bench reads back. The
    result dict carries ``kind`` so a client can dispatch on what it
    got back."""
    w = parse_workload(w)
    kind = w.kind
    _faults.fire(f"serving.workload.{kind}")
    _metrics.counter(f"serving.workload.{kind}.requests").inc()
    t0 = time.perf_counter()
    with _tracing.span(f"serving.workload.{kind}", model=engine.name,
                       version=engine.version):
        out = dict(w.run(engine))
    _metrics.histogram(f"serving.workload.{kind}.ms").observe(
        (time.perf_counter() - t0) * 1e3)
    out["kind"] = kind
    return out
