"""Typed serving workloads on the shared KV substrate (ISSUE 20).

One ``kind`` field on the wire selects among four request classes —
``generate``, ``constrained`` (TokenMaskSpec-masked logits),
``embed`` (prompt-only pooled hidden states + logprobs, zero decode
slots), and ``beam`` (k siblings over refcount-shared prompt pages).
See docs/SERVING.md § Workloads.
"""
from .base import (BeamWorkload, ConstrainedWorkload, EmbedWorkload,
                   GenerateWorkload, WORKLOAD_KINDS, Workload,
                   parse_workload, run_workload)
from .beam import beam_search
from .masks import MaskAutomaton, MaskError, TokenMaskSpec

__all__ = [
    "Workload", "GenerateWorkload", "ConstrainedWorkload",
    "EmbedWorkload", "BeamWorkload", "WORKLOAD_KINDS",
    "parse_workload", "run_workload", "beam_search",
    "TokenMaskSpec", "MaskAutomaton", "MaskError",
]
