"""n-best / beam decoding over SHARED prompt pages (ISSUE 20).

A beam here is not a new scheduler: it is k sibling requests forked
over the refcounted prefix index. One parent request decodes the
prompt once, asking for the fork position's top-k token order
(``topk_first``); its prompt pages publish into the PrefixIndex the
step its prefill completes. Each of the k children then submits
``prompt + [head_i]`` — ``alloc_prefix`` maps the parent's published
full pages by refcount (metadata only, no K/V copy) and COW-copies at
most the boundary tail page. The allocator's counters are the proof:
``prefix_shared_pages`` (entries with refs >= 2) rises while the
children are live, and each child's ``cached_tokens`` reports how much
prompt it never re-prefilled.

Because children are ordinary greedy requests under the per-(seed,
position) sampling contract, each beam's tail is BITWISE-equal to an
independent temperature-0 decode of ``prompt + [head_i]`` — page
sharing is invisible to the numerics (asserted in tier-1 against a
fresh engine with no prefix cache).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ..errors import ServingError


def beam_search(engine, prompt: Sequence[int], k: int = 2,
                max_new_tokens: int = 16,
                deadline_ms: Optional[float] = None,
                timeout: float = 300.0) -> Dict[str, Any]:
    """Decode the k best single-token forks of ``prompt`` to
    ``max_new_tokens`` each. Returns::

        {"beams": [[t_i, ...k tails...]], "prompt_len": P, "k": k,
         "cached_tokens": [per-child prefix-index hits],
         "shared_prompt_pages": refs>=2 pages while children live,
         "version": engine version}

    ``beams[0]`` is the greedy continuation. Requires the engine's
    prefix cache: without it every child would re-prefill the whole
    prompt and "beam" would silently be k independent decodes — the
    refusal is typed instead.
    """
    k = int(k)
    if k < 1:
        raise ValueError(f"beam width k must be >= 1, got {k}")
    max_new = int(max_new_tokens)
    if max_new < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
    if not engine.prefix_cache_enabled:
        raise ServingError(
            f"decoder '{engine.name}' has no prefix cache — beam "
            "children cannot share prompt pages (load it with "
            "prefix_cache=True)")
    if k > engine.spec.vocab:
        raise ValueError(
            f"beam width {k} exceeds vocab {engine.spec.vocab}")
    prompt = [int(t) for t in prompt]

    # parent: prefill once (publishing the prompt pages) and surface
    # the fork position's token order. Greedy on purpose — the fork
    # ranking must be the deterministic argsort of the step logits,
    # not a sample.
    parent = engine.generate(prompt, 1, deadline_ms=deadline_ms,
                             timeout=timeout, topk_first=k)
    heads = [int(t) for t in parent["first_topk"]]

    if max_new == 1:
        # no tails to decode; each beam IS its fork token
        return {"beams": [[h] for h in heads],
                "prompt_len": len(prompt), "k": k,
                "cached_tokens": [], "shared_prompt_pages": 0,
                "version": engine.version}

    # fork: submit all k children before waiting on any, so they share
    # the prompt pages CONCURRENTLY (alloc_prefix pins refcounts at
    # submit) and batch together in the scheduler
    reqs = [engine.submit(prompt + [h], max_new - 1,
                          deadline_ms=deadline_ms)
            for h in heads]
    # sharing evidence, sampled while every child holds its mapping:
    # pages referenced by >= 2 sequences right now. k beams over a
    # P-token prompt should map ~floor((P+1-1)/page_size) shared pages
    # once, not k copies
    pstats = engine.cache.allocator.prefix_stats() or {}
    shared = int(pstats.get("shared", 0))

    beams: List[List[int]] = []
    cached: List[int] = []
    first_err: Optional[BaseException] = None
    for h, req in zip(heads, reqs):
        if not req.ev.wait(timeout):
            if engine.cancel(req):
                if first_err is None:
                    first_err = ServingError(
                        f"beam child on '{engine.name}' timed out "
                        f"after {timeout}s")
                continue
        if req.error is not None:
            # keep draining the siblings (their pages must be freed by
            # completion, not abandoned), then surface the first error
            if first_err is None:
                first_err = req.error
            continue
        beams.append([h] + [int(t) for t in req.result["tokens"]])
        cached.append(int(req.result["cached_tokens"]))
    if first_err is not None:
        raise first_err

    return {"beams": beams, "prompt_len": len(prompt), "k": k,
            "cached_tokens": cached, "shared_prompt_pages": shared,
            "version": engine.version}
