"""Serializable token-mask specs for constrained decoding (ISSUE 20).

A ``TokenMaskSpec`` describes a language over TOKEN IDS (not bytes):
either a small regex over integer token literals, or an explicit list
of allowed token sequences.  ``compile()`` lowers the spec to a
``MaskAutomaton`` — a lazily determinized NFA whose per-state
``allowed(state, vocab)`` boolean vector is applied to the logits row
BEFORE ``sample_token``.  Because masking only subtracts probability
mass (disallowed lanes go to ``-inf``; softmax renormalizes over the
survivors) and the sampler is already deterministic per (seed,
position), a masked request emits bitwise the same tokens regardless
of what else shares its batch — the batch-composition-independence
the unconstrained path already proves carries over for free.

Regex syntax (whitespace separates atoms; token ids are decimal ints):

    7                one token
    7 9              concatenation
    7 | 9            alternation
    ( 7 9 ) *        grouping + Kleene star; ``+`` and ``?`` likewise
    .                any token in [0, vocab)
    [ 3 5 7 ]        token class
    [^ 0 1 ]         negated class (anything but 0 or 1)

The whole layer is host-side numpy over a [vocab] bool vector per
step — nothing here touches jit'd code, so constrained requests share
the engine's compiled shapes with every other workload kind.
"""
from __future__ import annotations

from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["TokenMaskSpec", "MaskAutomaton", "MaskError"]


class MaskError(ValueError):
    """A malformed mask spec (bad syntax, unknown kind, bad token id)."""


# -- pattern lexer/parser → Thompson NFA --------------------------------
#
# NFA edge labels: ("tok", i) | ("any",) | ("in", frozenset) |
# ("notin", frozenset); epsilon edges live in a separate list.  Each
# fragment has one start and one accept state (classic Thompson), so
# composition is pure bookkeeping.

_Label = Tuple[Any, ...]


class _Nfa:
    def __init__(self):
        self.edges: List[List[Tuple[_Label, int]]] = []
        self.eps: List[List[int]] = []

    def state(self) -> int:
        self.edges.append([])
        self.eps.append([])
        return len(self.edges) - 1

    def edge(self, src: int, label: _Label, dst: int):
        self.edges[src].append((label, dst))

    def epsilon(self, src: int, dst: int):
        self.eps[src].append(dst)


def _lex(pattern: str) -> List[str]:
    out: List[str] = []
    i, n = 0, len(pattern)
    while i < n:
        c = pattern[i]
        if c.isspace():
            i += 1
        elif c.isdigit():
            j = i
            while j < n and pattern[j].isdigit():
                j += 1
            out.append(pattern[i:j])
            i = j
        elif c in "|*+?()[].^":
            out.append(c)
            i += 1
        else:
            raise MaskError(f"mask regex: bad character {c!r} at {i}")
    return out


class _Parser:
    """Recursive descent over the lexed pattern:

        alt    := concat ('|' concat)*
        concat := repeat+
        repeat := atom ('*' | '+' | '?')*
        atom   := INT | '.' | '(' alt ')' | '[' '^'? INT+ ']'
    """

    def __init__(self, toks: List[str], nfa: _Nfa):
        self.toks = toks
        self.pos = 0
        self.nfa = nfa

    def peek(self) -> Optional[str]:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def take(self) -> str:
        t = self.peek()
        if t is None:
            raise MaskError("mask regex: unexpected end of pattern")
        self.pos += 1
        return t

    def parse(self) -> Tuple[int, int]:
        frag = self.alt()
        if self.peek() is not None:
            raise MaskError(f"mask regex: trailing {self.peek()!r}")
        return frag

    def alt(self) -> Tuple[int, int]:
        frags = [self.concat()]
        while self.peek() == "|":
            self.take()
            frags.append(self.concat())
        if len(frags) == 1:
            return frags[0]
        s, a = self.nfa.state(), self.nfa.state()
        for fs, fa in frags:
            self.nfa.epsilon(s, fs)
            self.nfa.epsilon(fa, a)
        return s, a

    def concat(self) -> Tuple[int, int]:
        frags = []
        while self.peek() is not None and self.peek() not in ")|":
            frags.append(self.repeat())
        if not frags:
            raise MaskError("mask regex: empty alternative")
        s, a = frags[0]
        for fs, fa in frags[1:]:
            self.nfa.epsilon(a, fs)
            a = fa
        return s, a

    def repeat(self) -> Tuple[int, int]:
        s, a = self.atom()
        while self.peek() in ("*", "+", "?"):
            op = self.take()
            ns, na = self.nfa.state(), self.nfa.state()
            self.nfa.epsilon(ns, s)
            self.nfa.epsilon(a, na)
            if op in ("*", "?"):
                self.nfa.epsilon(ns, na)
            if op in ("*", "+"):
                self.nfa.epsilon(a, s)
            s, a = ns, na
        return s, a

    def atom(self) -> Tuple[int, int]:
        t = self.take()
        if t == "(":
            frag = self.alt()
            if self.take() != ")":
                raise MaskError("mask regex: unbalanced '('")
            return frag
        s, a = self.nfa.state(), self.nfa.state()
        if t == ".":
            self.nfa.edge(s, ("any",), a)
        elif t == "[":
            neg = False
            if self.peek() == "^":
                self.take()
                neg = True
            ids = []
            while self.peek() is not None and self.peek() != "]":
                tok = self.take()
                if not tok.isdigit():
                    raise MaskError(f"mask regex: bad class member "
                                    f"{tok!r}")
                ids.append(int(tok))
            if self.take() != "]":  # consumed the "]" or raised
                raise MaskError("mask regex: unbalanced '['")
            if not ids:
                raise MaskError("mask regex: empty token class")
            fs = frozenset(ids)
            self.nfa.edge(s, ("notin", fs) if neg else ("in", fs), a)
        elif t.isdigit():
            self.nfa.edge(s, ("tok", int(t)), a)
        else:
            raise MaskError(f"mask regex: unexpected {t!r}")
        return s, a


class MaskAutomaton:
    """Lazily determinized token automaton.

    States are integers minted on first visit (state 0 is the start);
    ``allowed(state, vocab)`` yields the [vocab] bool vector of legal
    next tokens (cached per (state, vocab)), ``step(state, token)``
    advances (None = no transition), ``accepting(state)`` says whether
    the consumed prefix is a complete sentence of the language.
    Instances are immutable after construction apart from the memo
    dicts, and every mutation happens under the caller's single engine
    lock, so no locking of its own is needed.
    """

    def __init__(self, nfa: _Nfa, start: int, accept: int):
        self._nfa = nfa
        self._accept = accept
        self._sets: List[FrozenSet[int]] = []
        self._ids: Dict[FrozenSet[int], int] = {}
        self._allowed: Dict[Tuple[int, int], np.ndarray] = {}
        self._trans: Dict[Tuple[int, int], Optional[int]] = {}
        self.start = self._intern(self._closure({start}))

    # -- NFA plumbing ---------------------------------------------------
    def _closure(self, states) -> FrozenSet[int]:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for d in self._nfa.eps[s]:
                if d not in seen:
                    seen.add(d)
                    stack.append(d)
        return frozenset(seen)

    def _intern(self, sset: FrozenSet[int]) -> int:
        sid = self._ids.get(sset)
        if sid is None:
            sid = len(self._sets)
            self._ids[sset] = sid
            self._sets.append(sset)
        return sid

    @staticmethod
    def _matches(label: _Label, token: int) -> bool:
        kind = label[0]
        if kind == "tok":
            return token == label[1]
        if kind == "any":
            return True
        if kind == "in":
            return token in label[1]
        return token not in label[1]  # "notin"

    # -- public surface -------------------------------------------------
    def allowed(self, state: int, vocab: int) -> np.ndarray:
        key = (state, vocab)
        vec = self._allowed.get(key)
        if vec is None:
            vec = np.zeros(vocab, dtype=bool)
            for s in self._sets[state]:
                for label, _dst in self._nfa.edges[s]:
                    kind = label[0]
                    if kind == "tok":
                        if 0 <= label[1] < vocab:
                            vec[label[1]] = True
                    elif kind == "any":
                        vec[:] = True
                    elif kind == "in":
                        for t in label[1]:
                            if 0 <= t < vocab:
                                vec[t] = True
                    else:  # notin
                        neg = np.ones(vocab, dtype=bool)
                        for t in label[1]:
                            if 0 <= t < vocab:
                                neg[t] = False
                        vec |= neg
            vec.setflags(write=False)
            self._allowed[key] = vec
        return vec

    def step(self, state: int, token: int) -> Optional[int]:
        key = (state, int(token))
        if key in self._trans:
            return self._trans[key]
        move = set()
        for s in self._sets[state]:
            for label, dst in self._nfa.edges[s]:
                if self._matches(label, int(token)):
                    move.add(dst)
        nxt = self._intern(self._closure(move)) if move else None
        self._trans[key] = nxt
        return nxt

    def accepting(self, state: int) -> bool:
        return self._accept in self._sets[state]

    def max_token(self) -> int:
        """Largest token id named anywhere in the automaton (-1 if only
        wildcards/negations appear) — submit-time vocab validation."""
        hi = -1
        for edges in self._nfa.edges:
            for label, _dst in edges:
                kind = label[0]
                if kind == "tok":
                    hi = max(hi, label[1])
                elif kind in ("in", "notin"):
                    hi = max(hi, max(label[1]))
        return hi


class TokenMaskSpec:
    """Wire-serializable constraint: ``kind`` is ``"regex"`` (pattern
    over token ids, syntax in the module docstring) or ``"choices"``
    (explicit list of allowed token sequences)."""

    def __init__(self, kind: str, pattern: Optional[str] = None,
                 choices: Optional[Sequence[Sequence[int]]] = None):
        if kind == "regex":
            if not isinstance(pattern, str) or not pattern.strip():
                raise MaskError("regex mask needs a non-empty pattern")
            self.pattern: Optional[str] = pattern
            self.choices: Optional[List[List[int]]] = None
        elif kind == "choices":
            if not choices:
                raise MaskError("choices mask needs >= 1 sequence")
            seqs = []
            for seq in choices:
                seq = [int(t) for t in seq]
                if not seq or any(t < 0 for t in seq):
                    raise MaskError("choices must be non-empty lists "
                                    "of token ids >= 0")
                seqs.append(seq)
            self.pattern = None
            self.choices = seqs
        else:
            raise MaskError(f"unknown mask kind {kind!r}")
        self.kind = kind
        self._automaton: Optional[MaskAutomaton] = None

    @classmethod
    def regex(cls, pattern: str) -> "TokenMaskSpec":
        return cls("regex", pattern=pattern)

    @classmethod
    def one_of(cls, choices: Sequence[Sequence[int]]) -> "TokenMaskSpec":
        return cls("choices", choices=choices)

    def to_dict(self) -> Dict[str, Any]:
        if self.kind == "regex":
            return {"kind": "regex", "pattern": self.pattern}
        return {"kind": "choices", "choices": self.choices}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TokenMaskSpec":
        if not isinstance(d, dict):
            raise MaskError(f"mask spec must be a dict, got "
                            f"{type(d).__name__}")
        known = {"kind", "pattern", "choices"}
        extra = set(d) - known
        if extra:
            raise MaskError(f"mask spec has unknown keys {sorted(extra)}")
        return cls(d.get("kind", ""), pattern=d.get("pattern"),
                   choices=d.get("choices"))

    def compile(self) -> MaskAutomaton:
        if self._automaton is None:
            nfa = _Nfa()
            if self.kind == "regex":
                start, accept = _Parser(_lex(self.pattern or ""),
                                        nfa).parse()
            else:
                start, accept = nfa.state(), nfa.state()
                for seq in self.choices or []:
                    prev = start
                    for tok in seq:
                        nxt = nfa.state()
                        nfa.edge(prev, ("tok", tok), nxt)
                        prev = nxt
                    nfa.epsilon(prev, accept)
            self._automaton = MaskAutomaton(nfa, start, accept)
        return self._automaton
