"""InferenceEngine — shape-bucketed dynamic batching over one loaded model.

The TPU-shaped problem (PAPERS.md, Ragged Paged Attention; TVM's deploy
split): an online server sees arbitrary arrival patterns, but a compiled
accelerator program exists per SHAPE. Feeding each request's natural
batch size to the executor would mint a fresh XLA compile per novel
size — unbounded compile amplification under exactly the traffic that
can least afford it. The engine therefore drains its request queue into
batches padded up to a fixed BUCKET LADDER (e.g. 1/2/4/8/16): the
executor's jit cache is bounded at ``len(buckets)`` entries per model
version, every ladder entry is pre-compiled at load time (`warm`), and
the padded rows are sliced back off the outputs before requests are
answered.

Mechanics:

  - Requests enter a bounded queue (`submit`); past `max_queue` depth
    the engine raises ServerOverloaded IMMEDIATELY — admission control,
    not unbounded latency (the reject is ~free; the queue bound is the
    knob overload tests shrink under load).
  - One scheduler thread groups queued requests by shape key (the
    per-feed trailing dims + dtype), closes a batch when the largest
    bucket is covered or the OLDEST member has waited `max_wait_ms`
    (the batching timer: latency bound under trickle traffic), pads to
    the smallest bucket >= total rows, runs the model, and slices the
    per-request row ranges back out.
  - Every request may carry a deadline; lapsed requests are answered
    with DeadlineExceeded (counted in `serving.deadline_misses`) instead
    of burning compute that nobody is waiting for.
  - `stop(drain=True)` refuses new work but completes everything queued
    — the hot-swap path (registry.py) relies on this to retire an old
    version with zero dropped requests. After the scheduler exits, the
    engine drops its Program/Scope/Executor refs so the executor's
    WeakKeyDictionary jit cache releases the old version's compiled
    executables (regression-tested with weakrefs in tests/test_serving).

Two load paths (mirroring fluid/io.py's two artifacts):

  - `from_inference_dir`: a pruned Program via `load_inference_model`,
    run through a PRIVATE Executor + Scope (private so releasing the
    engine releases the compile cache, and so concurrent models never
    share a scope).
  - `from_exported_dir`: a StableHLO export via `load_exported_model`.
    The artifact was serialized at ONE batch size, so the ladder is that
    single bucket and every batch pads to it.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..autotune.ladder import observe as _observe_shape
from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger
from .errors import (DeadlineExceeded, EngineRetired, RequestTooLarge,
                     ServerOverloaded, ServingError)

__all__ = ["InferenceEngine", "parse_buckets", "default_buckets",
           "resolve_bucket_spec"]

_log = get_logger("serving")

# latency decomposition (ISSUE 5): where a request's time actually went.
# queue_wait = admission -> dequeued into a batch; batch_assemble = host
# concat+pad; compute = the model run (jit replay); total = admission ->
# response ready. A fat queue_wait with thin compute IS the overload /
# batching-timer signal, before anyone reads a timeline.
_m_queue_wait = _metrics.histogram("serving.queue_wait_ms")
_m_assemble = _metrics.histogram("serving.batch_assemble_ms")
_m_compute = _metrics.histogram("serving.compute_ms")
_m_total = _metrics.histogram("serving.total_ms")
# batching effectiveness: realized rows per batch, and the fraction of
# each padded batch that was padding (wasted compute) — the number that
# says whether the ladder fits the traffic
_m_batch_size = _metrics.histogram("serving.batch_size")
_m_pad_waste = _metrics.histogram("serving.padding_waste")
_m_requests = _metrics.counter("serving.requests")
_m_batches = _metrics.counter("serving.batches")
_m_overloads = _metrics.counter("serving.overloads")
_m_deadline_miss = _metrics.counter("serving.deadline_misses")


# the hand-set geometric ladder — the cold-cache fallback when "auto"
# has nothing observed and nothing cached (matches the FLAGS default)
_STATIC_BUCKETS = "1,2,4,8,16"


def default_buckets() -> List[int]:
    from ..fluid.flags import FLAGS

    return resolve_bucket_spec(FLAGS["serving_buckets"])


def _is_auto(spec) -> bool:
    return isinstance(spec, str) and spec.strip().lower() == "auto"


def resolve_bucket_spec(spec, *, tunable_id: str = "serving_buckets",
                        fallback: str = _STATIC_BUCKETS) -> List[int]:
    """``"auto"`` resolves through the tuner (ISSUE 8): a cached
    derived ladder for this device kind, else a ladder derived from the
    observed request-shape histogram, else the static default — the
    operator's FLAGS ladder when one is set (``tunable_id`` doubles as
    the FLAGS key), the shipped ``fallback`` only when the flag itself
    says "auto". Anything else parses as a literal ladder. Resolution
    happens ONCE, at engine load (before ``warm()``) — the ladder is
    fixed after warm, so the bounded-jit-cache / zero-post-warm-compiles
    invariants are untouched by autotuning."""
    if _is_auto(spec):
        from ..autotune.ladder import resolve_ladder
        from ..fluid.flags import FLAGS

        flag_val = FLAGS[tunable_id] if tunable_id in FLAGS else fallback
        base = fallback if _is_auto(flag_val) else flag_val
        return resolve_ladder(tunable_id, default=parse_buckets(base))
    return parse_buckets(spec)


def parse_buckets(spec) -> List[int]:
    """'1,2,4,8' (or an int sequence) -> sorted unique positive ladder."""
    if isinstance(spec, str):
        vals = [int(p) for p in spec.replace(";", ",").split(",") if p.strip()]
    else:
        vals = [int(v) for v in spec]
    vals = sorted(set(vals))
    if not vals or vals[0] < 1:
        raise ValueError(f"bucket ladder must be positive ints, got {spec!r}")
    return vals


def bucket_for(ladder: Sequence[int], n: int) -> int:
    """Smallest ladder entry >= n, clamped to the top bucket. The one
    bucket-selection rule for every engine in serving/ — admission
    bounds elsewhere keep n <= ladder[-1], so the clamp is defensive."""
    for b in ladder:
        if n <= b:
            return b
    return ladder[-1]


class _FeedSpec:
    """What the engine knows about one feed: trailing dims (-1 = free)
    and dtype. Requests are validated against it at ADMISSION (a shape
    mismatch fails fast with the feed named) and conformed to the dtype
    at assembly (a float64 array from a sloppy client must not mint a
    novel jit signature and break the ladder bound)."""

    __slots__ = ("name", "inner", "dtype")

    def __init__(self, name: str, inner: Tuple[int, ...], dtype: np.dtype):
        self.name = name
        self.inner = inner
        self.dtype = np.dtype(dtype)

    def check(self, arr: np.ndarray):
        if arr.ndim != len(self.inner) + 1:
            raise ValueError(
                f"feed '{self.name}' must be batched with "
                f"{len(self.inner) + 1} dims (batch first), got shape "
                f"{tuple(arr.shape)}")
        for want, got in zip(self.inner, arr.shape[1:]):
            if want != -1 and want != got:
                raise ValueError(
                    f"feed '{self.name}' expects trailing dims "
                    f"{self.inner}, got {tuple(arr.shape[1:])}")


class _Request:
    __slots__ = ("feeds", "rows", "key", "t_enq", "deadline", "ev",
                 "result", "error", "t_deq", "trace_ctx")

    def __init__(self, feeds, rows, key, deadline):
        self.feeds = feeds
        self.rows = rows
        self.key = key
        self.t_enq = time.monotonic()
        self.deadline = deadline  # absolute monotonic, or None
        self.ev = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.t_deq = 0.0
        # submitting thread's span context (None when tracing is off):
        # the scheduler adopts it so the batch span joins the request's
        # trace — a merged timeline reads client -> server -> engine
        self.trace_ctx = _tracing.wire_context()

    def fail(self, err: BaseException):
        self.error = err
        self.ev.set()


class InferenceEngine:
    """One loaded model version behind a batching scheduler thread."""

    def __init__(self, runner: Callable[[Dict[str, np.ndarray], int],
                                        List[np.ndarray]],
                 feed_specs: Sequence[_FeedSpec], fetch_names: Sequence[str],
                 *, name: str = "model", version: int = 1,
                 buckets: Optional[Sequence[int]] = None,
                 max_queue: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 kind: str = "program",
                 fetch_batched: Optional[Sequence[bool]] = None,
                 program=None, scope=None, executor=None):
        from ..fluid.flags import FLAGS

        self.name = str(name)
        self.version = int(version)
        self.kind = kind
        self._specs = list(feed_specs)
        self._feed_names = [s.name for s in self._specs]
        self._fetch_names = list(fetch_names)
        # which outputs are per-row (sliced back to each request) vs
        # whole (returned to every request): decided from the DECLARED
        # fetch-var shapes when available — a weight fetch whose first
        # dim coincidentally equals a bucket must never be mis-sliced.
        # None (exported artifacts carry no fetch shapes) falls back to
        # the shape[0]==bucket heuristic per batch.
        self._fetch_batched = (None if fetch_batched is None
                               else list(fetch_batched))
        self._buckets = resolve_bucket_spec(buckets) \
            if buckets is not None else default_buckets()
        self._max_batch = self._buckets[-1]
        self._max_queue = int(FLAGS["serving_max_queue"]
                              if max_queue is None
                              else max_queue)  # guarded-by: _cond
        self._max_wait = float(FLAGS["serving_max_wait_ms"]
                               if max_wait_ms is None else max_wait_ms) / 1e3
        # refs the release path drops (program mode); exported mode keeps
        # everything inside the runner closure. All _cond-guarded: stop()
        # drops them, warm()/the scheduler snapshot them under the lock.
        self._program = program  # guarded-by: _cond
        self._scope = scope  # guarded-by: _cond
        self._executor = executor  # guarded-by: _cond
        self._runner: Optional[Callable] = runner  # guarded-by: _cond
        self._cond = threading.Condition()
        self._queue: List[_Request] = []  # guarded-by: _cond
        self._stopping = False  # guarded-by: _cond
        self._released = False  # guarded-by: _cond
        self._n_requests = 0  # guarded-by: _cond
        self._n_batches = 0  # guarded-by: _cond
        # keyed by name AND version: during a hot-swap the draining old
        # engine and the live new one both report depth — sharing one
        # gauge would let the old engine's final 0 clobber the live
        # engine's real (possibly climbing) depth
        self._g_depth = _metrics.gauge(
            f"serving.queue_depth.{self.name}.v{self.version}")
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"serving-{self.name}-v{self.version}")
        self._thread.start()

    # -- construction -----------------------------------------------------
    @classmethod
    def from_inference_dir(cls, dirname: str, *, name: str = "model",
                           version: int = 1,
                           buckets: Optional[Sequence[int]] = None,
                           max_queue: Optional[int] = None,
                           max_wait_ms: Optional[float] = None,
                           warm: bool = True) -> "InferenceEngine":
        """Load a `save_inference_model` directory into a private
        Executor/Scope and (by default) pre-compile every ladder entry."""
        from ..fluid import io as _io
        from ..fluid.executor import Executor, Scope

        scope = Scope()
        exe = Executor()
        program, feed_names, fetch_vars = _io.load_inference_model(
            dirname, exe, scope=scope)
        block = program.global_block()
        specs = []
        for n in feed_names:
            var = block.var(n)
            inner = tuple(-1 if (d is None or int(d) < 0) else int(d)
                          for d in (var.shape or [-1])[1:])
            specs.append(_FeedSpec(n, inner, np.dtype(str(var.dtype))))
        fetch_names = [v.name for v in fetch_vars]
        # per-row iff the declared leading dim is the free batch dim; a
        # fetch with a CONSTANT leading dim (a weight, a reduced stat)
        # is returned whole to every request, never sliced — even if its
        # size coincides with a bucket. NOTE: batch-REDUCED fetches see
        # the padded+co-batched rows; serve per-row outputs and reduce
        # client-side if exact reduction semantics matter (docs/SERVING).
        fetch_batched = [
            v.shape is not None and len(v.shape) >= 1
            and (v.shape[0] is None or int(v.shape[0]) < 0)
            for v in fetch_vars
        ]

        def runner(feeds: Dict[str, np.ndarray], bucket: int):
            return exe.run(program, feed=feeds, fetch_list=fetch_names,
                           scope=scope)

        eng = cls(runner, specs, fetch_names, name=name, version=version,
                  buckets=buckets, max_queue=max_queue,
                  max_wait_ms=max_wait_ms, kind="program",
                  fetch_batched=fetch_batched,
                  program=program, scope=scope, executor=exe)
        if warm:
            try:
                eng.warm()
            except BaseException:
                # the constructor already started the scheduler thread;
                # a failed warmup (the registry's ROLLBACK path) must
                # not leak it — or the Program/Scope/Executor it pins
                eng.stop(drain=False)
                raise
        return eng

    @classmethod
    def from_exported_dir(cls, dirname: str, *, name: str = "model",
                          version: int = 1,
                          max_queue: Optional[int] = None,
                          max_wait_ms: Optional[float] = None,
                          warm: bool = True) -> "InferenceEngine":
        """Load an `export_compiled_model` StableHLO artifact. The export
        was serialized at one batch size, so the ladder is that single
        bucket — every batch pads to exactly the compiled shape."""
        from ..fluid import io as _io

        run, feed_meta, fetch_names = _io.load_exported_model(dirname)
        batch = int(feed_meta[0]["shape"][0])
        specs = [
            _FeedSpec(m["name"], tuple(int(d) for d in m["shape"][1:]),
                      np.dtype(m["dtype"]))
            for m in feed_meta
        ]
        order = [m["name"] for m in feed_meta]

        def runner(feeds: Dict[str, np.ndarray], bucket: int):
            return run(*[feeds[n] for n in order])

        eng = cls(runner, specs, fetch_names, name=name, version=version,
                  buckets=[batch], max_queue=max_queue,
                  max_wait_ms=max_wait_ms, kind="exported")
        if warm:
            try:
                eng.warm()
            except BaseException:
                eng.stop(drain=False)  # see from_inference_dir
                raise
        return eng

    # -- public surface ---------------------------------------------------
    @property
    def buckets(self) -> List[int]:
        return list(self._buckets)

    @property
    def program(self):
        """The loaded inference Program (None for exported artifacts, or
        after release) — exposed so lifecycle tests can weakref it."""
        with self._cond:  # _program is _cond-guarded (stop() drops it)
            return self._program

    def warm(self):
        """One synthetic batch per ladder entry: the full compile bill is
        paid at LOAD time (and a broken model fails here, where the
        registry can still roll back), never on live traffic. Free (-1)
        trailing dims warm at 1 — requests with other ragged shapes
        compile on first sight, one entry per distinct inner shape."""
        with self._cond:  # snapshot under the runner's guard
            runner = self._runner
        if runner is None:
            raise EngineRetired(f"model '{self.name}' released")
        with _tracing.span("serving.warmup", model=self.name,
                           version=self.version):
            for b in self._buckets:
                feeds = {
                    s.name: np.zeros(
                        (b,) + tuple(1 if d == -1 else d for d in s.inner),
                        dtype=s.dtype)
                    for s in self._specs
                }
                runner(feeds, b)

    def submit(self, feeds: Dict[str, Any],
               deadline_ms: Optional[float] = None) -> _Request:
        """Validate + enqueue. Raises ServerOverloaded / RequestTooLarge /
        EngineRetired / ValueError synchronously — admission is where
        structured rejection happens."""
        arrs: Dict[str, np.ndarray] = {}
        rows = None
        for spec in self._specs:
            if spec.name not in feeds:
                raise ValueError(
                    f"model '{self.name}' requires feed '{spec.name}' "
                    f"(wants {self._feed_names})")
            a = np.asarray(feeds[spec.name])
            spec.check(a)
            if a.dtype != spec.dtype:
                a = a.astype(spec.dtype)  # keep the jit signature canonical
            if rows is None:
                rows = int(a.shape[0])
            elif int(a.shape[0]) != rows:
                raise ValueError(
                    f"inconsistent batch dims across feeds: "
                    f"'{spec.name}' has {a.shape[0]} rows, expected {rows}")
            arrs[spec.name] = a
        if not rows:
            raise ValueError("empty request (zero rows)")
        # the tuner's shape recorder: every VALID request's row count —
        # including ones the incumbent ladder is about to refuse, or a
        # future auto-derived ladder could never learn to grow past the
        # current top bucket (autotune/ladder.py); metrics-cheap and
        # deliberately outside the engine lock
        _observe_shape("serving_buckets", rows)
        if rows > self._max_batch:
            raise RequestTooLarge(
                f"request of {rows} rows exceeds model '{self.name}' "
                f"largest bucket {self._max_batch} — shard it client-side")
        key = tuple((s.name, arrs[s.name].shape[1:], str(s.dtype))
                    for s in self._specs)
        deadline = (None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1e3)
        req = _Request(arrs, rows, key, deadline)
        with self._cond:
            if self._stopping:
                raise EngineRetired(
                    f"model '{self.name}' v{self.version} is retiring")
            if len(self._queue) >= self._max_queue:
                _m_overloads.inc()
                raise ServerOverloaded(
                    f"model '{self.name}' queue is full "
                    f"({self._max_queue} deep) — retry later or shed load")
            self._queue.append(req)
            self._n_requests += 1
            self._g_depth.set(len(self._queue))
            self._cond.notify()
        _m_requests.inc()
        return req

    def infer(self, feeds: Dict[str, Any],
              deadline_ms: Optional[float] = None,
              timeout: float = 120.0) -> Tuple[List[np.ndarray], int]:
        """Blocking convenience: submit + wait. Returns (outputs,
        version)."""
        req = self.submit(feeds, deadline_ms=deadline_ms)
        if not req.ev.wait(timeout):
            raise ServingError(
                f"infer on '{self.name}' timed out after {timeout}s "
                "(scheduler wedged?)")
        if req.error is not None:
            raise req.error
        return req.result, self.version

    def set_max_queue(self, n: int):
        """Live overload-control knob: shrink/grow the admission bound.
        Shrinking does not evict already-admitted requests — it only
        tightens future admissions."""
        with self._cond:
            self._max_queue = max(1, int(n))

    def stop(self, drain: bool = True, timeout: float = 120.0):
        """Refuse new work; `drain` completes the queue first, else the
        queue is failed with EngineRetired. Then the scheduler exits and
        every model ref (Program/Scope/Executor/runner) is dropped so
        the jit cache's compiled executables are released."""
        with self._cond:
            self._stopping = True
            if not drain:
                for r in self._queue:
                    r.fail(EngineRetired(
                        f"model '{self.name}' v{self.version} unloaded"))
                self._queue.clear()
                self._g_depth.set(0)
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():  # pragma: no cover - wedged scheduler
            _log.error("serving scheduler for %s v%d did not exit in %.0fs",
                       self.name, self.version, timeout)
        with self._cond:
            self._program = None
            self._scope = None
            self._executor = None
            self._runner = None
            self._released = True
            self._g_depth.set(0)  # a retired version holds no queue

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "name": self.name,
                "version": self.version,
                "kind": self.kind,
                "buckets": list(self._buckets),
                "feeds": self._feed_names,
                "fetches": list(self._fetch_names),
                "queue_depth": len(self._queue),
                "max_queue": self._max_queue,
                "max_wait_ms": self._max_wait * 1e3,
                "requests": self._n_requests,
                "batches": self._n_batches,
                "stopping": self._stopping,
            }

    # -- scheduler --------------------------------------------------------
    def _bucket_for(self, rows: int) -> int:
        return bucket_for(self._buckets, rows)

    def _loop(self):
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            try:
                self._run_batch(batch)
            except BaseException as e:  # a broken batch fails ITS requests
                _log.error("serving batch on %s v%d failed: %s: %s",
                           self.name, self.version, type(e).__name__, e)
                for r in batch:
                    if r.ev.is_set():
                        # already answered (e.g. failed DeadlineExceeded
                        # before the runner ran) — never overwrite an
                        # error a waiter may already be reading
                        continue
                    r.fail(e if isinstance(e, ServingError)
                           else ServingError(f"{type(e).__name__}: {e}"))

    def _drop_expired_locked(self, now: float):
        keep = []
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                _m_deadline_miss.inc()
                r.fail(DeadlineExceeded(
                    f"request to '{self.name}' missed its deadline while "
                    "queued"))
            else:
                keep.append(r)
        if len(keep) != len(self._queue):
            self._queue[:] = keep
            self._g_depth.set(len(keep))

    def _next_batch(self) -> Optional[List[_Request]]:
        # lint: allow-blocking — Condition.wait on the engine's own
        # condition is the scheduler's idle state by design
        with self._cond:
            while True:
                self._drop_expired_locked(time.monotonic())
                if not self._queue:
                    if self._stopping:
                        return None
                    self._cond.wait(0.1)
                    continue
                head = self._queue[0]
                avail = sum(r.rows for r in self._queue if r.key == head.key)
                waited = time.monotonic() - head.t_enq
                if (avail >= self._max_batch or waited >= self._max_wait
                        or self._stopping):
                    return self._pop_batch_locked(head.key)
                # batching timer: sleep only until the head's window
                # closes (capped so fresh arrivals re-evaluate promptly)
                self._cond.wait(min(self._max_wait - waited, 0.05))

    def _pop_batch_locked(self, key) -> List[_Request]:
        batch: List[_Request] = []
        rows = 0
        rest: List[_Request] = []
        now = time.monotonic()
        for r in self._queue:
            if r.key == key and rows + r.rows <= self._max_batch:
                r.t_deq = now
                batch.append(r)
                rows += r.rows
            else:
                rest.append(r)
        self._queue[:] = rest
        self._g_depth.set(len(rest))
        return batch

    def _run_batch(self, batch: List[_Request]):
        now = time.monotonic()
        live = []
        for r in batch:
            if r.deadline is not None and now > r.deadline:
                _m_deadline_miss.inc()
                r.fail(DeadlineExceeded(
                    f"request to '{self.name}' missed its deadline while "
                    "queued"))
            else:
                live.append(r)
        if not live:
            return
        rows = sum(r.rows for r in live)
        bucket = self._bucket_for(rows)
        t0 = time.perf_counter()
        feeds: Dict[str, np.ndarray] = {}
        for spec in self._specs:
            parts = [r.feeds[spec.name] for r in live]
            if bucket > rows:
                # pad with copies of the first row: always-valid data (an
                # all-zeros pad can NaN models with normalizing ops), and
                # the padded rows are sliced off before anyone sees them
                pad = np.broadcast_to(
                    parts[0][:1], (bucket - rows,) + parts[0].shape[1:])
                parts = parts + [pad]
            feeds[spec.name] = (parts[0] if len(parts) == 1
                                else np.concatenate(parts, axis=0))
        t1 = time.perf_counter()
        with self._cond:  # snapshot the runner under ITS guard
            runner = self._runner
            if runner is not None:
                self._n_batches += 1
        if runner is None:  # pragma: no cover - stop() raced a late batch
            for r in live:
                r.fail(EngineRetired(f"model '{self.name}' released"))
            return
        # adopt the batch-TRIGGERING (oldest) request's context: a span
        # has one parent, so the batch joins the head request's trace
        with _tracing.adopt(live[0].trace_ctx), \
                _tracing.span("serving.batch", model=self.name,
                              version=self.version, bucket=bucket,
                              rows=rows, requests=len(live)):
            outputs = [np.asarray(o) for o in runner(feeds, bucket)]
        t2 = time.perf_counter()
        _m_batches.inc()
        _m_batch_size.observe(rows)
        _m_pad_waste.observe((bucket - rows) / float(bucket))
        _m_assemble.observe((t1 - t0) * 1e3)
        _m_compute.observe((t2 - t1) * 1e3)
        end = time.monotonic()
        off = 0
        for r in live:
            sliced = []
            for j, o in enumerate(outputs):
                batched = (self._fetch_batched[j]
                           if self._fetch_batched is not None
                           else o.ndim >= 1 and o.shape[0] == bucket)
                sliced.append(o[off:off + r.rows]
                              if (batched and o.ndim >= 1
                                  and o.shape[0] == bucket) else o)
            off += r.rows
            if r.deadline is not None and end > r.deadline:
                _m_deadline_miss.inc()
                r.fail(DeadlineExceeded(
                    f"request to '{self.name}' finished after its "
                    "deadline"))
                continue
            r.result = sliced
            _m_queue_wait.observe((r.t_deq - r.t_enq) * 1e3)
            _m_total.observe((end - r.t_enq) * 1e3)
            r.ev.set()
