"""paddle_tpu.serving — online inference serving (ISSUE 5 + 6).

The runtime that consumes what `fluid/io.py` produces: load a
`save_inference_model` directory (or an `export_compiled_model`
StableHLO artifact) behind an `InferenceEngine` that batches requests
into a fixed bucket ladder, a `ModelRegistry` that hot-swaps versions
atomically, and a `ServingServer`/`ServingClient` pair on the
distributed RPC transport with admission control and chaos-ready
`serving.*` fault sites. Autoregressive decode (ISSUE 6) rides the
same registry/server: a `DecodeEngine` does continuous batching over a
paged KV cache (`kv_cache.py`) with a ragged paged-attention kernel,
served via the `generate`/`load_decoder` RPC methods. See
docs/SERVING.md.

    python -m paddle_tpu.serving --selftest   # in-process end-to-end
"""
from .client import ServingClient, TokenStream
from .decode import (DecodeEngine, DecoderSpec, sample_token,
                     validate_draft_spec)
from .engine import (InferenceEngine, default_buckets, parse_buckets,
                     resolve_bucket_spec)
from .errors import (DeadlineExceeded, EngineRetired, ModelNotFound,
                     RequestTooLarge, ServerOverloaded, ServingError,
                     StreamExpired)
from .kv_cache import PageAllocator, PagedKvCache
from .registry import ModelRegistry
from .server import ServingServer

__all__ = [
    "InferenceEngine", "DecodeEngine", "DecoderSpec", "ModelRegistry",
    "ServingServer", "ServingClient", "TokenStream", "PageAllocator",
    "PagedKvCache",
    "ServingError", "ServerOverloaded", "DeadlineExceeded",
    "ModelNotFound", "RequestTooLarge", "EngineRetired", "StreamExpired",
    "default_buckets", "parse_buckets", "resolve_bucket_spec",
    "sample_token", "validate_draft_spec",
]
