"""paddle_tpu.serving — online inference serving (ISSUE 5).

The runtime that consumes what `fluid/io.py` produces: load a
`save_inference_model` directory (or an `export_compiled_model`
StableHLO artifact) behind an `InferenceEngine` that batches requests
into a fixed bucket ladder, a `ModelRegistry` that hot-swaps versions
atomically, and a `ServingServer`/`ServingClient` pair on the
distributed RPC transport with admission control and chaos-ready
`serving.*` fault sites. See docs/SERVING.md.

    python -m paddle_tpu.serving --selftest   # in-process end-to-end
"""
from .client import ServingClient
from .engine import InferenceEngine, default_buckets, parse_buckets
from .errors import (DeadlineExceeded, EngineRetired, ModelNotFound,
                     RequestTooLarge, ServerOverloaded, ServingError)
from .registry import ModelRegistry
from .server import ServingServer

__all__ = [
    "InferenceEngine", "ModelRegistry", "ServingServer", "ServingClient",
    "ServingError", "ServerOverloaded", "DeadlineExceeded",
    "ModelNotFound", "RequestTooLarge", "EngineRetired",
    "default_buckets", "parse_buckets",
]
