"""Typed serving errors.

Every error a ServingServer hands back rides the RPC wire as the string
``"<TypeName>: <message>"`` (distributed/rpc.py wraps handler exceptions
that way); ServingClient parses the type name back out and re-raises the
matching class, so callers catch ``ServerOverloaded`` — a structured,
immediate admission rejection — instead of pattern-matching error
strings. Overload/deadline/not-found are APPLICATION errors: RpcClient
never retries them (retries are for transport failures only), which is
what makes an overloaded server shed load instead of being hammered by
its own rejected clients."""
from __future__ import annotations

__all__ = [
    "ServingError", "ServerOverloaded", "DeadlineExceeded",
    "ModelNotFound", "RequestTooLarge", "EngineRetired",
    "StreamExpired",
]


class ServingError(RuntimeError):
    """Base class for every structured serving failure."""


class ServerOverloaded(ServingError):
    """Admission control rejected the request: the model's bounded queue
    is full. Rejecting immediately keeps latency bounded for the
    requests already admitted — the alternative (unbounded queueing)
    turns overload into unbounded latency for everyone."""


class DeadlineExceeded(ServingError):
    """The request's deadline lapsed before a response could be
    produced (either while queued or by the time its batch finished)."""


class ModelNotFound(ServingError):
    """No model (or no live version) is registered under that name."""


class RequestTooLarge(ServingError):
    """A single request carries more rows than the model's largest
    batch bucket — it can never be scheduled; shard it client-side."""


class StreamExpired(ServingError):
    """A streaming-generate continuation named a stream id the server
    no longer holds: it was closed, its idle TTL lapsed (the abandoned-
    stream sweep canceled the sequence), or the server restarted. The
    caller restarts the stream — against a fleet, the router does this
    automatically, resuming from the last delivered offset."""


class EngineRetired(ServingError):
    """Internal hand-off signal: the engine stopped accepting work
    because a hot-swap retired it. The server catches this and resubmits
    to the registry's CURRENT engine, so a swap never fails a request —
    it should not normally escape to clients."""
