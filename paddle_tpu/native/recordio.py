"""RecordIO reader/writer — native (csrc/recordio.cc via ctypes) with a
pure-Python fallback implementing the identical on-disk format, so files are
interchangeable (reference paddle/fluid/recordio/, chunk.h:26)."""
from __future__ import annotations

import ctypes
import struct
import zlib
from typing import Iterator, List, Optional, Sequence

from . import load_native

MAGIC = b"PTRIO1\n\0"
DEFAULT_MAX_CHUNK = 1 << 20


class _PyWriter:
    def __init__(self, path: str, max_chunk_bytes: int = DEFAULT_MAX_CHUNK):
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._buf: List[bytes] = []
        self._size = 0
        self._max = max_chunk_bytes

    def write(self, record: bytes):
        self._buf.append(struct.pack("<I", len(record)) + record)
        self._size += len(record) + 4
        if self._size >= self._max:
            self._flush()

    def _flush(self):
        if not self._buf:
            return
        raw = b"".join(self._buf)
        comp = zlib.compress(raw)
        self._f.write(struct.pack("<IIII", len(self._buf), len(raw),
                                  len(comp), zlib.crc32(comp)))
        self._f.write(comp)
        self._buf, self._size = [], 0

    def close(self):
        self._flush()
        self._f.close()


class _PyReader:
    def __init__(self, path: str):
        self._f = open(path, "rb")
        if self._f.read(8) != MAGIC:
            self._f.close()
            raise IOError(f"{path}: not a recordio file")
        self._records: List[bytes] = []
        self._idx = 0

    def read(self) -> Optional[bytes]:
        while self._idx >= len(self._records):
            head = self._f.read(16)
            if not head:
                return None
            if len(head) != 16:
                raise IOError("truncated chunk header")
            _, raw_len, comp_len, crc = struct.unpack("<IIII", head)
            comp = self._f.read(comp_len)
            if len(comp) != comp_len or zlib.crc32(comp) != crc:
                raise IOError("corrupt chunk (crc mismatch)")
            raw = zlib.decompress(comp)
            if len(raw) != raw_len:
                raise IOError("corrupt chunk (length mismatch)")
            self._records, self._idx, pos = [], 0, 0
            while pos < len(raw):
                (n,) = struct.unpack_from("<I", raw, pos)
                pos += 4
                self._records.append(raw[pos:pos + n])
                pos += n
        rec = self._records[self._idx]
        self._idx += 1
        return rec

    def close(self):
        self._f.close()


class _CWriter:
    def __init__(self, lib, path: str, max_chunk_bytes: int):
        self._lib = lib
        self._h = lib.rio_writer_open(path.encode(), max_chunk_bytes)
        if not self._h:
            raise IOError(f"cannot open {path} for writing")

    def write(self, record: bytes):
        if self._lib.rio_writer_write(self._h, record, len(record)):
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            rc = self._lib.rio_writer_close(self._h)
            self._h = None
            if rc:
                raise IOError("recordio flush/close failed")


class _CReader:
    def __init__(self, lib, path: str):
        self._lib = lib
        self._h = lib.rio_reader_open(path.encode())
        if not self._h:
            raise IOError(f"{path}: not a recordio file")

    def read(self) -> Optional[bytes]:
        data = ctypes.POINTER(ctypes.c_char)()
        n = self._lib.rio_reader_next(self._h, ctypes.byref(data))
        if n == -1:
            return None
        if n < 0:
            raise IOError("corrupt recordio file")
        return ctypes.string_at(data, n)

    def close(self):
        if self._h:
            self._lib.rio_reader_close(self._h)
            self._h = None


def RecordIOWriter(path: str, max_chunk_bytes: int = DEFAULT_MAX_CHUNK):
    lib = load_native()
    if lib is not None:
        return _CWriter(lib, path, max_chunk_bytes)
    return _PyWriter(path, max_chunk_bytes)


def RecordIOReader(path: str):
    lib = load_native()
    if lib is not None:
        return _CReader(lib, path)
    return _PyReader(path)


def read_all(path: str) -> List[bytes]:
    r = RecordIOReader(path)
    out = []
    try:
        while True:
            rec = r.read()
            if rec is None:
                return out
            out.append(rec)
    finally:
        r.close()


def multi_file_reader(paths: Sequence[str], n_threads: int = 2,
                      queue_capacity: int = 256) -> Iterator[bytes]:
    """Threaded multi-file prefetch: C++ pool threads decompress chunks off
    the Python thread into a bounded channel (reference
    operators/reader/open_files_op.cc). Record order interleaves across
    files. Python fallback reads files sequentially."""
    lib = load_native()
    if lib is None:
        for p in paths:
            r = _PyReader(p)
            try:
                while True:
                    rec = r.read()
                    if rec is None:
                        break
                    yield rec
            finally:
                r.close()
        return

    arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
    h = lib.rio_multi_reader_open(arr, len(paths), n_threads, queue_capacity)
    try:
        data = ctypes.POINTER(ctypes.c_char)()
        while True:
            n = lib.rio_multi_reader_next(h, ctypes.byref(data))
            if n == -1:
                return
            if n < 0:
                raise IOError(
                    f"a recordio shard failed (corrupt or unreadable): {paths}"
                )
            yield ctypes.string_at(data, n)
    finally:
        lib.rio_multi_reader_close(h)
