"""Native runtime bindings (csrc/ C++ library via ctypes).

The reference implements its runtime plumbing in C++ (recordio chunks
`paddle/fluid/recordio/`, buddy allocator `paddle/fluid/memory/detail/`,
channels `paddle/fluid/framework/channel.h`, threadpool
`framework/threadpool.h`, threaded file readers
`operators/reader/open_files_op.cc`). This package is the TPU build's
native layer: the same capabilities compiled from csrc/ into
libpaddle_tpu_native.so, loaded with ctypes (no pybind11 in this
environment), built on demand with g++ and cached. Every consumer has a
pure-Python fallback so the framework degrades gracefully without a
toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     os.pardir, os.pardir, "csrc")
_SO = os.path.join(_CSRC, "libpaddle_tpu_native.so")

_lock = threading.Lock()
_lib = None
_load_failed = False


def _stale() -> bool:
    if not os.path.exists(_SO):
        return True
    so_m = os.path.getmtime(_SO)
    for f in os.listdir(_CSRC):
        if f.endswith((".cc", ".h")) and \
                os.path.getmtime(os.path.join(_CSRC, f)) > so_m:
            return True
    return False


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", _CSRC], check=True,
                       capture_output=True, timeout=300)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False


def _declare(lib):
    c = ctypes
    lib.rio_writer_open.restype = c.c_void_p
    lib.rio_writer_open.argtypes = [c.c_char_p, c.c_int]
    lib.rio_writer_write.restype = c.c_int
    lib.rio_writer_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.rio_writer_close.restype = c.c_int
    lib.rio_writer_close.argtypes = [c.c_void_p]
    lib.rio_reader_open.restype = c.c_void_p
    lib.rio_reader_open.argtypes = [c.c_char_p]
    lib.rio_reader_next.restype = c.c_int64
    lib.rio_reader_next.argtypes = [c.c_void_p, c.POINTER(c.POINTER(c.c_char))]
    lib.rio_reader_close.argtypes = [c.c_void_p]
    lib.rio_multi_reader_open.restype = c.c_void_p
    lib.rio_multi_reader_open.argtypes = [
        c.POINTER(c.c_char_p), c.c_int, c.c_int, c.c_int]
    lib.rio_multi_reader_next.restype = c.c_int64
    lib.rio_multi_reader_next.argtypes = [
        c.c_void_p, c.POINTER(c.POINTER(c.c_char))]
    lib.rio_multi_reader_close.argtypes = [c.c_void_p]

    lib.pt_buddy_create.restype = c.c_void_p
    lib.pt_buddy_create.argtypes = [c.c_uint64, c.c_uint64, c.c_int]
    lib.pt_buddy_alloc.restype = c.c_void_p
    lib.pt_buddy_alloc.argtypes = [c.c_void_p, c.c_uint64]
    lib.pt_buddy_free.restype = c.c_int
    lib.pt_buddy_free.argtypes = [c.c_void_p, c.c_void_p]
    lib.pt_buddy_used.restype = c.c_uint64
    lib.pt_buddy_used.argtypes = [c.c_void_p]
    lib.pt_buddy_check.restype = c.c_uint64
    lib.pt_buddy_check.argtypes = [c.c_void_p]
    lib.pt_buddy_quarantined.restype = c.c_uint64
    lib.pt_buddy_quarantined.argtypes = [c.c_void_p]
    lib.pt_buddy_total.restype = c.c_uint64
    lib.pt_buddy_total.argtypes = [c.c_void_p]
    lib.pt_buddy_destroy.argtypes = [c.c_void_p]

    lib.pt_chan_create.restype = c.c_void_p
    lib.pt_chan_create.argtypes = [c.c_int64]
    lib.pt_chan_send.restype = c.c_int
    lib.pt_chan_send.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.pt_chan_recv.restype = c.c_int64
    lib.pt_chan_recv.argtypes = [c.c_void_p, c.POINTER(c.POINTER(c.c_char))]
    lib.pt_buf_free.argtypes = [c.POINTER(c.c_char)]
    lib.pt_chan_try_send.restype = c.c_int
    lib.pt_chan_try_send.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.pt_chan_try_recv.restype = c.c_int64
    lib.pt_chan_try_recv.argtypes = [c.c_void_p, c.POINTER(c.POINTER(c.c_char))]
    lib.pt_chan_close.argtypes = [c.c_void_p]
    lib.pt_chan_size.restype = c.c_int64
    lib.pt_chan_size.argtypes = [c.c_void_p]
    lib.pt_chan_destroy.argtypes = [c.c_void_p]
    return lib


def load_native():
    """The loaded CDLL, building it first if missing/stale; None if the
    native library can't be built (consumers fall back to Python)."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if _stale() and not _build():
            _load_failed = True
            return None
        try:
            _lib = _declare(ctypes.CDLL(_SO))
        except OSError:
            _load_failed = True
        return _lib


def available() -> bool:
    return load_native() is not None


from . import channel, memory, recordio  # noqa: E402,F401
from .channel import Channel  # noqa: E402,F401
from .memory import BuddyAllocator  # noqa: E402,F401
from .recordio import RecordIOReader, RecordIOWriter, multi_file_reader  # noqa: E402,F401
