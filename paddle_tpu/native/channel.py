"""CSP channel of Python objects over the native ByteChannel
(csrc/channel.cc; reference framework/channel.h + channel_impl.h). Payloads
are pickled; capacity 0 = rendezvous like the reference's unbuffered
channel. Pure-Python fallback uses queue.Queue semantics."""
from __future__ import annotations

import contextlib
import ctypes
import pickle
import threading
from typing import Any, Optional, Tuple

from . import load_native


class ChannelClosed(Exception):
    pass


class _PyChannel:
    """Fallback mirroring ByteChannel's semantics (one condition variable,
    sequence-number rendezvous — csrc/channel.h)."""

    def __init__(self, capacity: int):
        import collections

        self._cap = capacity
        self._q = collections.deque()
        self._closed = False
        self._cv = threading.Condition()
        self._send_seq = 0
        self._pop_seq = 0
        self._recv_waiting = 0

    def send(self, obj) -> bool:
        with self._cv:
            if self._cap > 0:
                while not self._closed and len(self._q) >= self._cap:
                    self._cv.wait()
                if self._closed:
                    return False
                self._q.append(obj)
                self._cv.notify_all()
                return True
            if self._closed:
                return False
            self._send_seq += 1
            my_seq = self._send_seq
            self._q.append(obj)
            self._cv.notify_all()
            while not self._closed and self._pop_seq < my_seq:
                self._cv.wait()
            return self._pop_seq >= my_seq

    def recv(self) -> Tuple[bool, Any]:
        with self._cv:
            self._recv_waiting += 1
            while not self._closed and not self._q:
                self._cv.wait()
            self._recv_waiting -= 1
            if not self._q:
                return False, None
            obj = self._q.popleft()
            self._pop_seq += 1
            self._cv.notify_all()
            return True, obj

    def try_send(self, obj) -> str:
        with self._cv:
            if self._closed:
                return "closed"
            if self._cap > 0:
                if len(self._q) >= self._cap:
                    return "full"
            elif self._recv_waiting <= len(self._q):
                return "full"  # rendezvous: need a waiting receiver
            if self._cap == 0:
                self._send_seq += 1
            self._q.append(obj)
            self._cv.notify_all()
            return "sent"

    def try_recv(self):
        with self._cv:
            if self._q:
                obj = self._q.popleft()
                self._pop_seq += 1
                self._cv.notify_all()
                return "ok", obj
            return ("closed", None) if self._closed else ("empty", None)

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def destroy(self):
        self.close()

    def size(self) -> int:
        with self._cv:
            return len(self._q)


class Channel:
    """Blocking send/recv of arbitrary picklable objects.

    send(obj) -> bool (False if closed); recv() -> obj or raises
    ChannelClosed when closed and drained.

    Lifecycle: the native ByteChannel is freed by destroy() (also via the
    context-manager exit). Destruction is deferred while any thread is
    inside a native call on the handle — close() only wakes blocked
    waiters, it does not wait for them to leave the object, so freeing
    immediately would be a use-after-free under their feet. The last
    in-flight call performs the deferred free.
    """

    def __init__(self, capacity: int = 0):
        self._lib = load_native()
        self._mu = threading.Lock()
        self._inflight = 0
        self._destroy_pending = False
        if self._lib is not None:
            self._h: Optional[int] = self._lib.pt_chan_create(capacity)
            self._py = None
        else:
            self._h = None
            self._py = _PyChannel(capacity)

    class _Destroyed(Exception):
        """Internal: the handle is already freed (or being freed)."""

    @contextlib.contextmanager
    def _native_call(self):
        """Guards a native call: holds the handle alive until it returns."""
        with self._mu:
            if self._h is None or self._destroy_pending:
                raise Channel._Destroyed()
            self._inflight += 1
            h = self._h
        try:
            yield h
        finally:
            with self._mu:
                self._inflight -= 1
                if (self._destroy_pending and self._inflight == 0
                        and self._h is not None):
                    self._lib.pt_chan_destroy(self._h)
                    self._h = None

    def send(self, obj) -> bool:
        if self._py is not None:
            return self._py.send(obj)
        data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            with self._native_call() as h:
                return self._lib.pt_chan_send(h, data, len(data)) == 0
        except Channel._Destroyed:
            return False  # destroyed == closed for the send contract

    def recv(self):
        if self._py is not None:
            ok, obj = self._py.recv()
            if not ok:
                raise ChannelClosed()
            return obj
        out = ctypes.POINTER(ctypes.c_char)()
        try:
            with self._native_call() as h:
                n = self._lib.pt_chan_recv(h, ctypes.byref(out))
                if n < 0:
                    raise ChannelClosed()
                try:
                    return pickle.loads(ctypes.string_at(out, n))
                finally:
                    self._lib.pt_buf_free(out)
        except Channel._Destroyed:
            raise ChannelClosed() from None

    def try_send(self, obj) -> str:
        """'sent' | 'full' | 'closed' — non-blocking (Select cases)."""
        if self._py is not None:
            return self._py.try_send(obj)
        data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            with self._native_call() as h:
                rc = self._lib.pt_chan_try_send(h, data, len(data))
        except Channel._Destroyed:
            return "closed"
        return "sent" if rc == 1 else ("full" if rc == 0 else "closed")

    def try_recv(self):
        """(status, value): 'ok' | 'empty' | 'closed' — non-blocking."""
        if self._py is not None:
            return self._py.try_recv()
        out = ctypes.POINTER(ctypes.c_char)()
        try:
            with self._native_call() as h:
                n = self._lib.pt_chan_try_recv(h, ctypes.byref(out))
                if n == -2:
                    return "empty", None
                if n == -1:
                    return "closed", None
                try:
                    return "ok", pickle.loads(ctypes.string_at(out, n))
                finally:
                    self._lib.pt_buf_free(out)
        except Channel._Destroyed:
            return "closed", None

    def close(self):
        if self._py is not None:
            self._py.close()
            return
        # go through the in-flight guard: close must not race a concurrent
        # destroy() freeing the handle under us
        try:
            with self._native_call() as h:
                self._lib.pt_chan_close(h)
        except Channel._Destroyed:
            pass  # already freed (or being freed) -> closed by definition

    def destroy(self):
        """Close and free the native channel. Safe while other threads are
        blocked in send/recv: they are woken by the close and the last one
        out frees the handle."""
        if self._py is not None:
            self._py.destroy()
            return
        self.close()
        with self._mu:
            if self._h is None:
                return
            if self._inflight == 0:
                self._lib.pt_chan_destroy(self._h)
                self._h = None
            else:
                self._destroy_pending = True

    def size(self) -> int:
        if self._py is not None:
            return self._py.size()
        try:
            with self._native_call() as h:
                return int(self._lib.pt_chan_size(h))
        except Channel._Destroyed:
            return 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.destroy()
        return False

    def __iter__(self):
        while True:
            try:
                yield self.recv()
            except ChannelClosed:
                return

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass
