"""CSP channel of Python objects over the native ByteChannel
(csrc/channel.cc; reference framework/channel.h + channel_impl.h). Payloads
are pickled; capacity 0 = rendezvous like the reference's unbuffered
channel. Pure-Python fallback uses queue.Queue semantics."""
from __future__ import annotations

import ctypes
import pickle
import queue
import threading
from typing import Any, Optional, Tuple

from . import load_native


class ChannelClosed(Exception):
    pass


class _PyChannel:
    """Fallback with the same close/rendezvous semantics."""

    def __init__(self, capacity: int):
        self._q = queue.Queue(maxsize=max(capacity, 0) or 1)
        self._rendezvous = capacity == 0
        self._closed = threading.Event()
        self._pop_cv = threading.Condition()
        self._pops = 0

    def send(self, obj) -> bool:
        if self._closed.is_set():
            return False
        if not self._rendezvous:
            while True:
                if self._closed.is_set():
                    return False
                try:
                    self._q.put(obj, timeout=0.05)
                    return True
                except queue.Full:
                    continue
        with self._pop_cv:
            target = self._pops + self._q.qsize() + 1
            self._q.put(obj)
            while self._pops < target and not self._closed.is_set():
                self._pop_cv.wait(0.05)
            return self._pops >= target

    def recv(self) -> Tuple[bool, Any]:
        while True:
            try:
                obj = self._q.get(timeout=0.05)
                with self._pop_cv:
                    self._pops += 1
                    self._pop_cv.notify_all()
                return True, obj
            except queue.Empty:
                if self._closed.is_set() and self._q.empty():
                    return False, None

    def try_send(self, obj) -> str:
        if self._closed.is_set():
            return "closed"
        if self._rendezvous:
            return "full"  # no waiting-receiver bookkeeping in the fallback
        try:
            self._q.put_nowait(obj)
            return "sent"
        except queue.Full:
            return "full"

    def try_recv(self):
        try:
            obj = self._q.get_nowait()
            with self._pop_cv:
                self._pops += 1
                self._pop_cv.notify_all()
            return "ok", obj
        except queue.Empty:
            if self._closed.is_set():
                return "closed", None
            return "empty", None

    def close(self):
        self._closed.set()
        with self._pop_cv:
            self._pop_cv.notify_all()

    def destroy(self):
        self.close()

    def size(self) -> int:
        return self._q.qsize()


class Channel:
    """Blocking send/recv of arbitrary picklable objects.

    send(obj) -> bool (False if closed); recv() -> obj or raises
    ChannelClosed when closed and drained.
    """

    def __init__(self, capacity: int = 0):
        self._lib = load_native()
        if self._lib is not None:
            self._h: Optional[int] = self._lib.pt_chan_create(capacity)
            self._py = None
        else:
            self._h = None
            self._py = _PyChannel(capacity)

    def send(self, obj) -> bool:
        data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        if self._py is not None:
            return self._py.send(obj)
        return self._lib.pt_chan_send(self._h, data, len(data)) == 0

    def recv(self):
        if self._py is not None:
            ok, obj = self._py.recv()
            if not ok:
                raise ChannelClosed()
            return obj
        out = ctypes.POINTER(ctypes.c_char)()
        n = self._lib.pt_chan_recv(self._h, ctypes.byref(out))
        if n < 0:
            raise ChannelClosed()
        try:
            return pickle.loads(ctypes.string_at(out, n))
        finally:
            self._lib.pt_buf_free(out)

    def try_send(self, obj) -> str:
        """'sent' | 'full' | 'closed' — non-blocking (Select cases)."""
        if self._py is not None:
            return self._py.try_send(obj)
        data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        rc = self._lib.pt_chan_try_send(self._h, data, len(data))
        return "sent" if rc == 1 else ("full" if rc == 0 else "closed")

    def try_recv(self):
        """(status, value): 'ok' | 'empty' | 'closed' — non-blocking."""
        if self._py is not None:
            return self._py.try_recv()
        out = ctypes.POINTER(ctypes.c_char)()
        n = self._lib.pt_chan_try_recv(self._h, ctypes.byref(out))
        if n == -2:
            return "empty", None
        if n == -1:
            return "closed", None
        try:
            return "ok", pickle.loads(ctypes.string_at(out, n))
        finally:
            self._lib.pt_buf_free(out)

    def close(self):
        if self._py is not None:
            self._py.close()
        elif self._h:
            self._lib.pt_chan_close(self._h)

    def size(self) -> int:
        if self._py is not None:
            return self._py.size()
        return int(self._lib.pt_chan_size(self._h))

    def __iter__(self):
        while True:
            try:
                yield self.recv()
            except ChannelClosed:
                return

    def __del__(self):
        try:
            if self._h and self._lib is not None:
                self._lib.pt_chan_close(self._h)
                self._lib.pt_chan_destroy(self._h)
                self._h = None
        except Exception:
            pass
