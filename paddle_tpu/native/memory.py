"""Host buddy allocator (csrc/buddy_allocator.cc) — the staging-buffer side
of the reference's memory layer (paddle/fluid/memory/detail/
buddy_allocator.h:33; device HBM itself is managed by PJRT on TPU).

numpy views into the arena let input pipelines fill buffers without per-batch
allocation. Pure-Python fallback: plain numpy allocation (same API)."""
from __future__ import annotations

import ctypes
from typing import Dict, Optional

import numpy as np

from . import load_native


class BuddyAllocator:
    def __init__(self, total_bytes: int, min_block: int = 256):
        self._lib = load_native()
        self._handles: Dict[int, int] = {}
        if self._lib is not None:
            self._h = self._lib.pt_buddy_create(total_bytes, min_block)
            if not self._h:
                raise MemoryError("buddy arena allocation failed")
        else:
            self._h = None
            self._total = total_bytes
            self._used = 0

    def alloc(self, nbytes: int, dtype="uint8") -> Optional[np.ndarray]:
        """A numpy array view over a fresh block (None if arena exhausted)."""
        dt = np.dtype(dtype)
        n = nbytes * dt.itemsize if dtype != "uint8" else nbytes
        if self._h is not None:
            p = self._lib.pt_buddy_alloc(self._h, n)
            if not p:
                return None
            buf = (ctypes.c_char * n).from_address(p)
            arr = np.frombuffer(buf, dtype=dt)
            self._handles[id(arr)] = p
            return arr
        self._used += n
        if self._used > self._total:
            self._used -= n
            return None
        arr = np.zeros(n // dt.itemsize, dtype=dt)
        self._handles[id(arr)] = 0
        return arr

    def free(self, arr: np.ndarray):
        p = self._handles.pop(id(arr), None)
        if p is None:
            raise ValueError("array was not allocated by this allocator")
        if self._h is not None:
            if self._lib.pt_buddy_free(self._h, p):
                raise ValueError("double free or bad pointer")
        else:
            self._used -= arr.nbytes

    def memory_usage(self) -> int:
        """Bytes currently allocated (reference memory::memory_usage)."""
        if self._h is not None:
            return int(self._lib.pt_buddy_used(self._h))
        return self._used

    @property
    def total(self) -> int:
        if self._h is not None:
            return int(self._lib.pt_buddy_total(self._h))
        return self._total

    def close(self):
        if self._h is not None:
            self._lib.pt_buddy_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
