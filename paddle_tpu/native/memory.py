"""Host buddy allocator (csrc/buddy_allocator.cc) — the staging-buffer side
of the reference's memory layer (paddle/fluid/memory/detail/
buddy_allocator.h:33; device HBM itself is managed by PJRT on TPU).

numpy views into the arena let input pipelines fill buffers without per-batch
allocation. Pure-Python fallback: plain numpy allocation (same API)."""
from __future__ import annotations

import ctypes
from typing import Dict, Optional

import numpy as np

from . import load_native


class BuddyAllocator:
    def __init__(self, total_bytes: int, min_block: int = 256,
                 guard: str = "slack"):
        """guard='slack' stamps canaries only in a block's natural slack
        (zero capacity overhead; exact power-of-two requests go
        unguarded); guard='always' bumps near-power-of-two requests one
        block level so every allocation carries a guard region."""
        if guard not in ("slack", "always"):
            raise ValueError("guard must be 'slack' or 'always'")
        self._lib = load_native()
        self._handles: Dict[int, int] = {}
        if self._lib is not None:
            self._h = self._lib.pt_buddy_create(
                total_bytes, min_block, 1 if guard == "always" else 0)
            if not self._h:
                raise MemoryError("buddy arena allocation failed")
        else:
            self._h = None
            self._total = total_bytes
            self._used = 0

    def alloc(self, count: int, dtype="uint8") -> Optional[np.ndarray]:
        """A numpy view over a fresh block of `count` elements of `dtype`
        (bytes for the default uint8); None if the arena is exhausted.
        Blocks must be returned with free() — dropping the view without
        freeing leaks its block (the allocator keeps the view alive in its
        ledger until then)."""
        dt = np.dtype(dtype)
        n = count * dt.itemsize
        if self._h is not None:
            p = self._lib.pt_buddy_alloc(self._h, n)
            if not p:
                return None
            buf = (ctypes.c_char * n).from_address(p)
            arr = np.frombuffer(buf, dtype=dt)
            # hold the view: keeps id(arr) unique for the ledger's lifetime
            self._handles[id(arr)] = (p, arr)
            return arr
        self._used += n
        if self._used > self._total:
            self._used -= n
            return None
        arr = np.zeros(count, dtype=dt)
        self._handles[id(arr)] = (0, arr)
        return arr

    def free(self, arr: np.ndarray):
        entry = self._handles.get(id(arr))
        if entry is None or entry[1] is not arr:
            raise ValueError("array was not allocated by this allocator")
        del self._handles[id(arr)]
        if self._h is not None:
            rc = self._lib.pt_buddy_free(self._h, entry[0])
            if rc == -1:
                raise ValueError("double free or bad pointer")
            if rc == -2:
                # Guard bytes past the requested size were clobbered. The
                # allocator QUARANTINES the block (it never re-enters the
                # free lists), so the damaged memory cannot be handed out
                # again before this error is handled.
                raise MemoryError(
                    "heap overwrite detected: guard bytes past the block's "
                    "requested size were clobbered; block quarantined "
                    "(reference meta_cache guard check)")
        else:
            self._used -= arr.nbytes

    def check(self) -> int:
        """Sweep all live blocks' guard regions; returns the number of
        corrupted blocks (reference memory/detail/meta_cache.cc guards —
        the §5.2 memory-debug capability)."""
        if self._h is not None:
            return int(self._lib.pt_buddy_check(self._h))
        return 0

    def quarantined(self) -> int:
        """Bytes permanently held out of the arena after guard-corruption
        detection (containment beats reuse of damaged memory)."""
        if self._h is not None:
            return int(self._lib.pt_buddy_quarantined(self._h))
        return 0

    def memory_usage(self) -> int:
        """Bytes currently allocated (reference memory::memory_usage)."""
        if self._h is not None:
            return int(self._lib.pt_buddy_used(self._h))
        return self._used

    @property
    def total(self) -> int:
        if self._h is not None:
            return int(self._lib.pt_buddy_total(self._h))
        return self._total

    def close(self):
        if self._h is not None:
            self._lib.pt_buddy_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
