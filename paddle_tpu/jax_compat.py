"""jax version shims. This image ships jax 0.4.37, where shard_map lives
in jax.experimental and the replication-check kwarg is `check_rep`; newer
jax exports `jax.shard_map` with `check_vma`. Callers import from here so
one file owns the skew."""
from __future__ import annotations

try:  # jax >= 0.6
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})


def make_device_mesh(axes, devices=None):
    """Named-axis device Mesh construction, one place for any topology
    skew (ISSUE 15). ``axes``: ordered {name: size}. Uses the first
    prod(sizes) devices when more are available (tier-1's virtual
    8-device CPU mesh frequently outnumbers a 2-way test mesh); on TPU
    prefers ``mesh_utils.create_device_mesh`` for ICI-aware ordering,
    off-TPU a plain reshape (virtual CPU devices have no topology).
    Typed error when devices run short."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    names = tuple(str(n) for n in axes)
    shape = tuple(int(axes[n]) for n in axes)
    need = int(np.prod(shape))
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < need:
        raise ValueError(
            f"mesh {dict(zip(names, shape))} needs {need} devices, have "
            f"{len(devs)} — off-TPU set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N")
    devs = devs[:need]
    if devices is None and devs and devs[0].platform == "tpu":
        try:  # ICI-topology-aware ordering where the backend knows one
            from jax.experimental import mesh_utils

            return Mesh(mesh_utils.create_device_mesh(shape), names)
        except Exception:  # pragma: no cover - odd topologies fall back
            pass
    return Mesh(np.asarray(devs).reshape(shape), names)


# collective HLO spellings as they appear in StableHLO / HLO text; the
# keys are the counter suffixes mesh.observe registers
_COLLECTIVE_OPS = (
    ("all_reduce", ("all_reduce", "all-reduce")),
    ("all_gather", ("all_gather", "all-gather")),
    ("reduce_scatter", ("reduce_scatter", "reduce-scatter")),
    ("collective_permute", ("collective_permute", "collective-permute")),
    ("all_to_all", ("all_to_all", "all-to-all")),
)


def collective_counts(lowered_text: str) -> dict:
    """Count collective ops in a lowered/compiled program's text — the
    compile-time evidence of what the SPMD partitioner inserted (host
    code cannot time individual device collectives; it CAN count them
    exactly). Returns {kind: count} with zero entries elided."""
    out = {}
    for kind, spellings in _COLLECTIVE_OPS:
        n = 0
        for s in spellings:
            n += lowered_text.count(f"stablehlo.{s} ") + \
                lowered_text.count(f"stablehlo.{s}(")
            n += lowered_text.count(f" {s}(")  # HLO text form
        if n:
            out[kind] = n
    return out


def cost_analysis_dict(stage) -> dict:
    """Normalize `.cost_analysis()` across jax versions and stage kinds.

    On this image (jax 0.4.37) `Lowered.cost_analysis()` returns a flat
    dict (and costs only an HLO walk — no XLA compile), while
    `Compiled.cost_analysis()` returns a ONE-ELEMENT LIST of per-device
    dicts; newer jax returns a dict from both. Returns {} when the
    backend offers no analysis — callers treat cost accounting as
    best-effort evidence, never a hard dependency.
    """
    try:
        ca = stage.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    return {str(k): float(v) for k, v in ca.items()
            if isinstance(v, (int, float))}


def memory_analysis_dict(compiled) -> dict:
    """`Compiled.memory_analysis()` -> plain byte-count dict ({} when the
    backend doesn't implement it). Field names follow the XLA
    CompiledMemoryStats attributes present on this jaxlib."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for k in ("generated_code_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "temp_size_in_bytes",
              "alias_size_in_bytes", "host_temp_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    return out
