"""jax version shims. This image ships jax 0.4.37, where shard_map lives
in jax.experimental and the replication-check kwarg is `check_rep`; newer
jax exports `jax.shard_map` with `check_vma`. Callers import from here so
one file owns the skew."""
from __future__ import annotations

try:  # jax >= 0.6
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})
