"""jax version shims. This image ships jax 0.4.37, where shard_map lives
in jax.experimental and the replication-check kwarg is `check_rep`; newer
jax exports `jax.shard_map` with `check_vma`. Callers import from here so
one file owns the skew."""
from __future__ import annotations

try:  # jax >= 0.6
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})


def cost_analysis_dict(stage) -> dict:
    """Normalize `.cost_analysis()` across jax versions and stage kinds.

    On this image (jax 0.4.37) `Lowered.cost_analysis()` returns a flat
    dict (and costs only an HLO walk — no XLA compile), while
    `Compiled.cost_analysis()` returns a ONE-ELEMENT LIST of per-device
    dicts; newer jax returns a dict from both. Returns {} when the
    backend offers no analysis — callers treat cost accounting as
    best-effort evidence, never a hard dependency.
    """
    try:
        ca = stage.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    return {str(k): float(v) for k, v in ca.items()
            if isinstance(v, (int, float))}


def memory_analysis_dict(compiled) -> dict:
    """`Compiled.memory_analysis()` -> plain byte-count dict ({} when the
    backend doesn't implement it). Field names follow the XLA
    CompiledMemoryStats attributes present on this jaxlib."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for k in ("generated_code_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "temp_size_in_bytes",
              "alias_size_in_bytes", "host_temp_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    return out
