"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

No reference counterpart (2018 — the reference's only model partitioning is
per-layer `device` placement in the legacy config, SURVEY.md §2.10). This is
the TPU-native capability: stage parameters live sharded over the `pp` mesh
axis (leading stage dim), activations flow stage-to-stage over ICI via
`lax.ppermute`, and the whole schedule is one XLA computation — fully
differentiable (ppermute transposes to the reverse rotation), so a jitted
training step backpropagates through the pipeline for free.

Layout contract: every stage has the same signature
    stage_fn(stage_params, x) -> y        (x, y same shape [mb, ...])
and `params` is a pytree whose leaves are stacked on a leading stage axis of
size n_stages (shard that axis over `pp`).
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _shift_right(x, axis_name, n):
    """Send each device's value to the next stage; stage 0 receives zeros
    (ring edge n-1 -> 0 is cut)."""
    perm = [(j, j + 1) for j in range(n - 1)]
    return lax.ppermute(x, axis_name, perm)


def pipeline_apply_shard(stage_fn: Callable, stage_params, x_mb,
                         axis_name: str):
    """Per-shard GPipe schedule (run under shard_map over `axis_name`).

    stage_params: this device's stage parameters (leading stage axis of size
    1, squeezed here). x_mb: [n_micro, mb, ...] microbatches — replicated
    (every stage sees them; only stage 0 consumes them). Returns
    [n_micro, mb, ...] outputs (valid on the last stage, zeros elsewhere —
    the global wrapper broadcasts them back).

    Schedule: T = n_micro + n_stages - 1 ticks. At tick t, stage s computes
    microbatch t - s (when in range). Each tick every device runs stage_fn
    once (idle ticks compute on garbage and are masked out) — the classic
    GPipe bubble of (n_stages - 1) / T.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: jnp.squeeze(p, 0), stage_params)
    n_micro = x_mb.shape[0]
    mb_shape = x_mb.shape[1:]
    ticks = n_micro + n - 1

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 reads microbatch t (clamped; masked when out of range),
        # other stages read what the previous stage sent last tick
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        first_in = lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        cur_in = jnp.where(idx == 0, first_in, recv)
        out = stage_fn(params, cur_in)
        # last stage stores microbatch t - (n-1) when it's valid
        out_idx = jnp.clip(t - (n - 1), 0, n_micro - 1)
        valid = jnp.logical_and(idx == n - 1, t >= n - 1)
        store = jnp.where(valid, out, 0.0)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
            + store,
            out_idx, 0,
        )
        recv = _shift_right(out, axis_name, n) if n > 1 else out
        return (recv, outputs), None

    recv0 = jnp.zeros(mb_shape, x_mb.dtype)
    out0 = jnp.zeros((n_micro,) + mb_shape, x_mb.dtype)
    (_, outputs), _ = lax.scan(tick, (recv0, out0), jnp.arange(ticks))
    # broadcast last stage's outputs to every device so out_specs can be
    # replicated over pp (psum: all other stages hold zeros)
    return lax.psum(outputs, axis_name)


def pipeline_apply(
    stage_fn: Callable, params, x, mesh: Mesh, axis_name: str = "pp",
    n_microbatches: Optional[int] = None,
):
    """Global entry point. params: pytree with leaves stacked on a leading
    stage axis (length = pp axis size); x: [batch, ...] global input.
    Splits batch into microbatches, pipelines them, returns [batch, ...]."""
    n_stages = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    n_micro = n_microbatches or n_stages
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by {n_micro} microbatches")
    x_mb = x.reshape((n_micro, b // n_micro) + x.shape[1:])

    pspec = jax.tree.map(lambda _: P(axis_name), params)
    fn = shard_map(
        functools.partial(pipeline_apply_shard, stage_fn,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        check_vma=False,
    )
    out_mb = fn(params, x_mb)
    return out_mb.reshape((b,) + out_mb.shape[2:])


def stack_stage_params(per_stage_params):
    """[params_stage0, params_stage1, ...] (matching pytrees) -> one pytree
    with a leading stage axis, ready to shard over pp."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, 0), *per_stage_params)


def shard_stage_params(params, mesh: Mesh, axis_name: str = "pp"):
    """Place stacked stage params with the leading axis sharded over pp."""
    def _put(p):
        spec = P(axis_name, *([None] * (p.ndim - 1)))
        return jax.device_put(p, NamedSharding(mesh, spec))

    return jax.tree.map(_put, params)
