"""Mixture-of-Experts with expert parallelism over a mesh axis.

No reference counterpart (2018). TPU-native design: Switch/GShard-style
dense dispatch — routing is expressed as one-hot einsums with static
capacity (XLA-friendly: no dynamic shapes), expert weights carry a leading
expert axis sharded over `ep`, and sharding constraints make XLA's SPMD
partitioner insert the token all-to-alls over ICI.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _constrain(x, mesh: Optional[Mesh], spec: P):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def top1_dispatch(gates, capacity: int):
    """Switch-style top-1 routing. gates: [T, E] softmax probs. Returns
    (dispatch [T, E, C] one-hot, combine [T, E, C] gate-weighted, aux_loss).
    Tokens beyond an expert's capacity C are dropped (output 0 for them —
    the residual connection around the MoE layer carries them through)."""
    t, e = gates.shape
    expert_idx = jnp.argmax(gates, axis=-1)                     # [T]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=gates.dtype)   # [T, E]
    # load-balancing aux loss (Switch Transformer eq. 4):
    # E * sum_e (fraction of tokens to e) * (mean gate prob of e)
    density = onehot.mean(axis=0)
    density_proxy = gates.mean(axis=0)
    aux_loss = (density * density_proxy).sum() * e
    # position of each token within its expert's queue
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot           # [T, E]
    pos = pos.sum(axis=-1)                                      # [T]
    keep = (pos < capacity).astype(gates.dtype)
    onehot = onehot * keep[:, None]
    pos_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=gates.dtype)  # [T, C]
    dispatch = onehot[:, :, None] * pos_onehot[:, None, :]      # [T, E, C]
    gate_val = (gates * onehot).sum(axis=-1)                    # [T]
    combine = dispatch * gate_val[:, None, None]
    return dispatch, combine, aux_loss


def moe_ffn(
    x, router_w, w1, w2,
    mesh: Optional[Mesh] = None, ep_axis: str = "ep",
    capacity_factor: float = 1.25, activation=jax.nn.relu,
) -> Tuple[jax.Array, jax.Array]:
    """MoE feed-forward. x: [..., d]; router_w: [d, E]; w1: [E, d, ff];
    w2: [E, ff, d]. Returns (out [..., d], aux_loss scalar).

    The [E, ...] dims of the dispatched activations are constrained to shard
    over `ep_axis`; with w1/w2 sharded the same way each device computes only
    its experts and XLA all-to-alls the tokens in and out.
    """
    d = x.shape[-1]
    e = router_w.shape[1]
    xt = x.reshape(-1, d)
    t = xt.shape[0]
    capacity = int(np.ceil(t / e * capacity_factor))

    logits = jnp.einsum("td,de->te", xt, router_w,
                        preferred_element_type=jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, aux_loss = top1_dispatch(gates, capacity)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, xt)
    expert_in = _constrain(expert_in, mesh, P(ep_axis, None, None))
    h = activation(jnp.einsum("ecd,edf->ecf", expert_in, w1))
    h = _constrain(h, mesh, P(ep_axis, None, None))
    expert_out = jnp.einsum("ecf,efd->ecd", h, w2)
    expert_out = _constrain(expert_out, mesh, P(ep_axis, None, None))
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.reshape(x.shape), aux_loss.astype(jnp.float32)
