"""Sharding plans: the TPU-native analog of the reference's
distribute_transpiler (python/paddle/fluid/distribute_transpiler.py:136) —
instead of rewriting the program into trainer+pserver halves, a plan maps
var names to PartitionSpecs over a named Mesh; the same lowered block runs
SPMD with XLA-inserted collectives.
"""
from __future__ import annotations

import contextlib
import re
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec as P

_mesh_tls = threading.local()


def current_mesh() -> Optional[Mesh]:
    """The mesh active during program lowering, if any. Op emitters that need
    explicit SPMD (ring attention's shard_map) read it here; None means
    single-device lowering."""
    return getattr(_mesh_tls, "mesh", None)


@contextlib.contextmanager
def mesh_context(mesh: Optional[Mesh]):
    prev = current_mesh()
    _mesh_tls.mesh = mesh
    try:
        yield mesh
    finally:
        _mesh_tls.mesh = prev


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Build a named mesh, e.g. make_mesh({'dp': 2, 'tp': 4}).
    Axis sizes must multiply to the device count."""
    devs = list(devices) if devices is not None else jax.devices()
    shape = tuple(axes.values())
    if int(np.prod(shape)) != len(devs):
        raise ValueError(
            f"mesh {axes} needs {int(np.prod(shape))} devices, have {len(devs)}"
        )
    return Mesh(np.asarray(devs).reshape(shape), tuple(axes.keys()))


class ShardingPlan:
    """Maps var-name patterns (regex) -> PartitionSpec. First match wins;
    unmatched vars are replicated."""

    def __init__(self, rules: Sequence[Tuple[str, P]] = (),
                 batch_axis: Optional[str] = "dp",
                 seq_axis: Optional[str] = None,
                 best_effort: bool = False):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.batch_axis = batch_axis
        self.seq_axis = seq_axis
        # best_effort: an indivisible dim falls back to replication instead
        # of erroring (catch-all plans like plan_fsdp, where odd-width
        # biases simply stay replicated)
        self.best_effort = best_effort

    def add(self, pattern: str, spec: P) -> "ShardingPlan":
        self.rules.append((re.compile(pattern), spec))
        return self

    def spec_for(self, name: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(name):
                if len(spec) > ndim:
                    # rules intentionally also match optimizer accumulators
                    # derived from a param name; scalar accumulators
                    # (beta pows, lr) can't take the param's spec — replicate
                    return P()
                return spec
        return P()

    def feed_spec(self, ndim: int) -> P:
        if self.batch_axis is None or ndim == 0:
            return P()
        if self.seq_axis is not None and ndim >= 2:
            # sequence-parallel feeds: [batch, seq, ...] shard both leading dims
            return P(self.batch_axis, self.seq_axis, *([None] * (ndim - 2)))
        return P(self.batch_axis, *([None] * (ndim - 1)))


def plan_data_parallel() -> ShardingPlan:
    """Pure DP: feeds sharded on batch, all state replicated — what the
    reference ParallelExecutor's NCCL all-reduce graph computes."""
    return ShardingPlan(batch_axis="dp")


def plan_transformer_tp() -> ShardingPlan:
    """Megatron-style tensor parallel for models/transformer.py: attention
    q/k/v and ffn first matmul shard on the output (head) axis, attention
    out-proj and ffn second matmul shard on the input axis, embeddings shard
    on vocab; XLA inserts the all-reduces at the row-parallel boundaries."""
    # the `(_\w+)?$` tails also catch optimizer accumulators derived from the
    # param name (e.g. "enc0.self.q.w_moment1_0"), keeping Adam moments
    # sharded alongside their params
    return ShardingPlan(
        rules=[
            (r"\.(q|k|v)\.w(_\w+)?$", P(None, "tp")),
            (r"\.ff1\.w(_\w+)?$", P(None, "tp")),
            (r"\.out\.w(_\w+)?$", P("tp", None)),
            (r"\.ff2\.w(_\w+)?$", P("tp", None)),
            (r"\.emb(_\w+)?$", P("tp", None)),
            (r"^proj\.w(_\w+)?$", P(None, "tp")),
        ],
        batch_axis="dp",
    )


def plan_moe_ep(batch_axis: str = "dp", ep_axis: str = "ep") -> ShardingPlan:
    """Expert parallelism: expert weight stacks ([E, ...], created by
    layers.moe as `<name>.experts.w{1,2}`) shard their expert axis over ep;
    router + everything else replicated; feeds on batch."""
    return ShardingPlan(
        rules=[(r"\.experts\.w[12](_\w+)?$", P(ep_axis))],
        batch_axis=batch_axis,
    )


def plan_fsdp(batch_axis: str = "dp", shard_axis: Optional[str] = None
              ) -> ShardingPlan:
    """ZeRO/FSDP-style fully sharded data parallel (the scaling-book
    recipe; no 2018-reference equivalent — its multi-GPU path replicates
    params and NCCL-all-reduces grads): every parameter AND its optimizer
    accumulators shard dim 0 over the data axis. GSPMD then all-gathers
    a weight just before its use and reduce-scatters its gradient —
    per-chip parameter+optimizer memory drops by the dp degree while the
    math stays exactly data parallel. Scalar state (lr, beta pows) is
    replicated by ShardingPlan's ndim guard."""
    axis = shard_axis or batch_axis
    # one catch-all rule: any named var (params and their `<p>_moment...`
    # accumulators alike) shards dim 0; spec_for's len(spec)>ndim guard
    # keeps scalars replicated, and best_effort keeps odd-width tensors
    # (a [10]-class bias on dp=8) replicated instead of erroring
    return ShardingPlan(rules=[(r".", P(axis))], batch_axis=batch_axis,
                        best_effort=True)


def plan_sequence_parallel(batch_axis: str = "dp",
                           seq_axis: str = "sp") -> ShardingPlan:
    """Context parallelism: feeds shard on [batch, seq]; params replicated.
    Attention itself must use a sequence-parallel op (ring/ulysses, see
    sequence_parallel.py) — pointwise/fc layers shard over seq for free
    under GSPMD."""
    return ShardingPlan(batch_axis=batch_axis, seq_axis=seq_axis)
