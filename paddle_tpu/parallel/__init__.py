"""Parallelism toolkit: meshes, sharding plans, collective ops.

TPU-native replacement for the reference's distributed stack (SURVEY.md
§2.10): where the reference inserts NCCL op-handles / gRPC send-recv into the
program, here parallelism is expressed as jax.sharding specs over a device
Mesh and XLA's SPMD partitioner inserts the ICI collectives.
"""
from .api import (  # noqa: F401
    ShardingPlan,
    current_mesh,
    make_mesh,
    mesh_context,
    plan_data_parallel,
    plan_fsdp,
    plan_moe_ep,
    plan_sequence_parallel,
    plan_transformer_tp,
)
from .moe import moe_ffn, top1_dispatch  # noqa: F401
from .pipeline import (  # noqa: F401
    pipeline_apply,
    shard_stage_params,
    stack_stage_params,
)
from .sequence_parallel import (  # noqa: F401
    ring_attention_shard,
    sequence_parallel_attention,
    ulysses_attention_shard,
)
