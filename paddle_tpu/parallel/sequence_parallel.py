"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference's long-sequence story is LoD variable-length tensors
(`paddle/fluid/framework/lod_tensor.h:44-110`) — 2018 has no sequence
parallelism. The TPU-native capability extension (SURVEY.md §5.7) shards the
*sequence axis* of attention across the ICI mesh:

  - **Ring attention** (`ring_attention_shard`): each device holds a sequence
    chunk of Q/K/V; K/V blocks rotate around the ring via `lax.ppermute`
    while a flash-style online softmax (running max / sum) accumulates the
    local queries' output. Memory per device is O(S/n), and each ppermute
    overlaps with the next block's matmuls. The backward pass is a second
    ring pass (custom_vjp): dK/dV accumulators travel with their K/V blocks.
  - **Ulysses** (`ulysses_attention_shard`): `lax.all_to_all` re-shards
    [B, S/n, H, D] -> [B, S, H/n, D] so each device runs full-sequence
    attention on a head subset, then the inverse all_to_all restores
    sequence sharding. Differentiable through the collectives' transposes.

Both are per-shard functions to be run under `shard_map`;
`sequence_parallel_attention` is the global-array wrapper.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _bhq_to_bqh1(x):
    # [B,H,Sq] -> [B,Sq,H,1] (broadcast factor for the [B,Sq,H,D] accumulator)
    return x.transpose(0, 2, 1)[..., None]


def _block_scores(q32, k, scale, mask):
    s = jnp.einsum("bqhd,bkhd->bhqk", q32, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    return s


def _online_softmax_block(q32, k, v, m, l, o, mask, scale):
    """One flash-attention block update. m,l: [B,H,Sq] f32 running max/sum;
    o: [B,Sq,H,D] f32 unnormalized output accumulator."""
    s = _block_scores(q32, k, scale, mask)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # rows with no valid key yet keep m = NEG_INF; exp(0)=1 there would
    # poison p, so masked score entries are explicitly zeroed
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o_new = o * _bhq_to_bqh1(alpha) + pv
    return m_new, l_new, o_new


def _ring_perm(n):
    return [(j, (j + 1) % n) for j in range(n)]


def _causal_mask(my, src, sq, sk):
    """Block mask for query chunk `my` against key chunk originally at `src`
    (chunks are contiguous sequence slices of equal length per device)."""
    qpos = my * sq + jnp.arange(sq)
    kpos = src * sk + jnp.arange(sk)
    return (qpos[:, None] >= kpos[None, :])[None, None]  # [1,1,Sq,Sk]


def _axis_size(axis_name) -> int:
    """Static axis size: lax.psum of a concrete 1 constant-folds to the
    axis size at trace time — no collective, no device code."""
    if axis_name is None:
        return 1
    return lax.psum(1, axis_name)


def _ring_fwd_pass(q, k, v, my, axis_name, causal, scale):
    n = _axis_size(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    q32 = q.astype(jnp.float32)
    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, sq, h, d), jnp.float32)

    def step(carry, i):
        m, l, o, kk, vv = carry
        src = (my - i) % n
        mask = _causal_mask(my, src, sq, sk) if causal else None
        m, l, o = _online_softmax_block(q32, kk, vv, m, l, o, mask, scale)
        kk = lax.ppermute(kk, axis_name, _ring_perm(n))
        vv = lax.ppermute(vv, axis_name, _ring_perm(n))
        return (m, l, o, kk, vv), None

    # scan the first n-1 blocks (each ends with a K/V rotation), then fold in
    # the final block outside the loop — its rotation would be discarded
    if n > 1:
        (m, l, o, k, v), _ = lax.scan(
            step, (m0, l0, o0, k, v), jnp.arange(n - 1)
        )
    else:
        m, l, o = m0, l0, o0
    last_src = (my - (n - 1)) % n
    last_mask = _causal_mask(my, last_src, sq, sk) if causal else None
    m, l, o = _online_softmax_block(q32, k, v, m, l, o, last_mask, scale)
    l_safe = jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
    out = (o / _bhq_to_bqh1(l_safe)).astype(q.dtype)
    lse = m + jnp.log(l_safe)  # [B,H,Sq]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ring_attention_impl(q, k, v, my_idx, axis_name, causal, scale):
    """custom_vjp core: ``my_idx`` is an int32[1] array carrying THIS
    shard's ring position. It is a primal input (zero float0 cotangent)
    rather than ``lax.axis_index(axis_name)`` because axis_index lowers
    to the ``partition-id`` HLO, which jax-0.4.37's CPU SPMD partitioner
    rejects whenever jaxpr DCE leaves it alive ("PartitionId instruction
    is not supported for SPMD partitioning") — the skew that aborted the
    dryrun's ring phases. A sharded-iota input says the same thing in
    data, which every backend partitions."""
    out, _ = _ring_fwd_pass(q, k, v, my_idx[0], axis_name, causal, scale)
    return out


def ring_attention_shard(q, k, v, axis_name=None, causal=False,
                         scale: Optional[float] = None, my_idx=None):
    """Per-shard ring attention. q: [B, Sq_local, H, D]; k/v: [B, Sk_local,
    H, D], sequence-sharded over `axis_name` (None = single chunk, plain
    flash attention). Softmax in f32; output in q.dtype.

    ``my_idx`` (int32[1]): this shard's ring position, normally threaded
    in by ``sequence_parallel_attention`` as a P(seq_axis)-sharded iota.
    Direct shard_map users on newer jax may omit it (falls back to
    ``lax.axis_index`` — fine there, but that path lowers to the
    partition-id HLO the 0.4.x CPU partitioner rejects)."""
    scale = float(scale) if scale is not None else q.shape[-1] ** -0.5
    if my_idx is None:
        if axis_name is None:
            my_idx = jnp.zeros((1,), jnp.int32)
        else:
            my_idx = lax.axis_index(axis_name).reshape(1).astype(jnp.int32)
    return _ring_attention_impl(q, k, v, my_idx, axis_name, causal, scale)


def _ring_fwd_rule(q, k, v, my_idx, axis_name, causal, scale):
    out, lse = _ring_fwd_pass(q, k, v, my_idx[0], axis_name, causal, scale)
    return out, (q, k, v, my_idx, out, lse)


def _ring_bwd_rule(axis_name, causal, scale, res, dout):
    q, k, v, my_idx, out, lse = res
    n, my = _axis_size(axis_name), my_idx[0]
    b, sq, h, d = q.shape
    sk = k.shape[1]
    q32 = q.astype(jnp.float32)
    do32 = dout.astype(jnp.float32)
    # D_i = sum_d dO_i * O_i, the softmax-jacobian diagonal term: [B,H,Sq]
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1).transpose(0, 2, 1)

    dq0 = jnp.zeros((b, sq, h, d), jnp.float32)
    dk0 = jnp.zeros_like(k, dtype=jnp.float32)
    dv0 = jnp.zeros_like(v, dtype=jnp.float32)

    def step(carry, i):
        dq, dk, dv, kk, vv = carry
        src = (my - i) % n
        mask = _causal_mask(my, src, sq, sk) if causal else None
        s = _block_scores(q32, kk, scale, mask)
        p = jnp.exp(s - lse[..., None])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv = dv + jnp.einsum("bhqk,bqhd->bkhd", p, do32,
                             preferred_element_type=jnp.float32)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do32, vv.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, kk.astype(jnp.float32),
                             preferred_element_type=jnp.float32) * scale
        dk = dk + jnp.einsum("bhqk,bqhd->bkhd", ds, q32,
                             preferred_element_type=jnp.float32) * scale
        if axis_name is not None and n > 1:
            # dK/dV accumulators travel with their K/V blocks; after n hops
            # every block is back on its home device with all contributions
            kk, vv, dk, dv = (
                lax.ppermute(x, axis_name, _ring_perm(n))
                for x in (kk, vv, dk, dv)
            )
        return (dq, dk, dv, kk, vv), None

    (dq, dk, dv, _, _), _ = lax.scan(
        step, (dq0, dk0, dv0, k, v), jnp.arange(n)
    )
    # my_idx is an integer primal: its cotangent is the float0 zero
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            np.zeros(np.shape(my_idx), jax.dtypes.float0))


_ring_attention_impl.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ulysses_attention_shard(q, k, v, axis_name, causal=False,
                            scale: Optional[float] = None):
    """Per-shard Ulysses attention: all_to_all heads<->sequence, then full
    attention on a head subset. Requires H %% axis_size == 0."""
    n = _axis_size(axis_name)
    if n > 1:
        if q.shape[2] % n:
            raise ValueError(
                f"ulysses needs heads ({q.shape[2]}) divisible by axis size {n}"
            )
        a2a = functools.partial(lax.all_to_all, axis_name=axis_name,
                                split_axis=2, concat_axis=1, tiled=True)
        q, k, v = a2a(q), a2a(k), a2a(v)  # -> [B, S, H/n, D]
    out = ring_attention_shard(q, k, v, None, causal, scale)
    if n > 1:
        out = lax.all_to_all(out, axis_name=axis_name, split_axis=1,
                             concat_axis=2, tiled=True)
    return out


def sequence_parallel_attention(
    q, k, v, mesh: Mesh, seq_axis: str = "sp",
    batch_axis: Optional[str] = None, head_axis: Optional[str] = None,
    causal: bool = False, scale: Optional[float] = None, impl: str = "ring",
):
    """Global-array entry point: q/k/v are [B, S, H, D] jax.Arrays; the
    sequence dim is sharded over `seq_axis` of `mesh` (batch over
    `batch_axis`, heads over `head_axis` when given) and attention runs
    SPMD via shard_map."""
    if impl == "ring":
        def body(qs, ks, vs, idx):
            return ring_attention_shard(qs, ks, vs, seq_axis, causal,
                                        scale, my_idx=idx)
    elif impl == "ulysses":
        def body(qs, ks, vs, idx):
            del idx  # ulysses needs only the axis SIZE, never the index
            return ulysses_attention_shard(qs, ks, vs, seq_axis,
                                           causal=causal, scale=scale)
    else:
        raise ValueError(f"unknown sequence-parallel impl '{impl}'")
    spec = P(batch_axis, seq_axis, head_axis, None)
    # the ring index rides in as DATA: a P(seq_axis)-sharded iota hands
    # each shard its own position, so the body never calls
    # lax.axis_index (whose partition-id lowering the jax-0.4.x CPU
    # SPMD partitioner rejects — the `PartitionId` dryrun skew)
    n_sp = dict(zip(mesh.axis_names, mesh.devices.shape))[seq_axis]
    ring_idx = jnp.arange(n_sp, dtype=jnp.int32)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec, P(seq_axis)),
                   out_specs=spec, check_vma=False)
    # Pin the boundary shardings explicitly. Under GSPMD the producers
    # (e.g. tp column-parallel qkv projections) already carry compatible
    # shardings when head_axis matches the plan; the constraints make that
    # contract visible to the partitioner so it reshards with a local
    # slice/relabel instead of discovering a conflict at the shard_map edge
    # and falling back to full rematerialization (spmd_partitioner.cc:652).
    cons = lambda x: jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, spec))
    idx = jax.lax.with_sharding_constraint(
        ring_idx, jax.sharding.NamedSharding(mesh, P(seq_axis)))
    out = fn(cons(q), cons(k), cons(v), idx)
    return cons(out)
