"""ResNet for ImageNet/cifar10 (reference benchmark/fluid/resnet.py:90-173 —
conv_bn_layer/shortcut/bottleneck/layer_warp structure; the north-star
benchmark model).

`fused=True` builds every conv+bn(+relu) chain as the single
conv2d_bn_relu op (the Pallas blocked-GEMM alternate kernel under
FLAGS['use_pallas_kernels'], plain fused XLA otherwise) — the
inference-serving form, where bn is a frozen per-channel affine
(reference inference conv+bn fuse passes / conv_mkldnn_op.cc)."""
from __future__ import annotations

from ..fluid import layers


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_test=False, fused=False):
    if fused:
        return layers.conv2d_bn_relu(
            input, num_filters=ch_out, filter_size=filter_size,
            stride=stride, padding=padding, relu=(act == "relu"))
    conv = layers.conv2d(
        input=input, num_filters=ch_out, filter_size=filter_size,
        stride=stride, padding=padding, act=None, bias_attr=False,
    )
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def shortcut(input, ch_out, stride, is_test=False, fused=False):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_test=is_test, fused=fused)
    return input


def basicblock(input, ch_out, stride, is_test=False, fused=False):
    short = shortcut(input, ch_out, stride, is_test=is_test, fused=fused)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test,
                          fused=fused)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test,
                          fused=fused)
    return layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_out, stride, is_test=False, fused=False):
    short = shortcut(input, ch_out * 4, stride, is_test=is_test, fused=fused)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test,
                          fused=fused)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test,
                          fused=fused)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test, fused=fused)
    return layers.elementwise_add(x=short, y=conv3, act="relu")


def layer_warp(block_func, input, ch_out, count, stride, is_test=False,
               fused=False):
    res_out = block_func(input, ch_out, stride, is_test=is_test, fused=fused)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_test=is_test,
                             fused=fused)
    return res_out


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False,
                    fused=False):
    cfg = {
        18: ([2, 2, 2, 1], basicblock),
        34: ([3, 4, 6, 3], basicblock),
        50: ([3, 4, 6, 3], bottleneck),
        101: ([3, 4, 23, 3], bottleneck),
        152: ([3, 8, 36, 3], bottleneck),
    }
    stages, block_func = cfg[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_test=is_test, fused=fused)
    pool1 = layers.pool2d(input=conv1, pool_type="max", pool_size=3,
                          pool_stride=2, pool_padding=1)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, is_test=is_test,
                      fused=fused)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, is_test=is_test,
                      fused=fused)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, is_test=is_test,
                      fused=fused)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, is_test=is_test,
                      fused=fused)
    pool2 = layers.pool2d(input=res4, pool_size=7, pool_type="avg",
                          global_pooling=True)
    return layers.fc(input=pool2, size=class_dim)


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False,
                   fused=False):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_test=is_test, fused=fused)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_test=is_test,
                      fused=fused)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_test=is_test,
                      fused=fused)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_test=is_test,
                      fused=fused)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                         global_pooling=True)
    return layers.fc(input=pool, size=class_dim)


def build_train(img, label, class_dim=1000, depth=50, variant="imagenet",
                is_test=False, fused=False):
    """Returns (avg_cost, accuracy, prediction)."""
    model = resnet_imagenet if variant == "imagenet" else resnet_cifar10
    logits = model(img, class_dim=class_dim, depth=depth, is_test=is_test,
                   fused=fused)
    cost = layers.softmax_with_cross_entropy(logits=logits, label=label)
    avg_cost = layers.mean(cost)
    prediction = layers.softmax(logits)
    acc = layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction
