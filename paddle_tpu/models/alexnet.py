"""AlexNet (reference benchmark/alexnet.py, legacy v2 benchmark suite).

The reference's headline legacy-GPU table (benchmark/README.md:33-40) trains
this network at bs=128/512 on a K40m; `benchmarks/legacy_conv_bench.py`
reproduces that workload on TPU through the Program IR stack.

Architecture is the standard one-tower AlexNet (5 conv + 3 fc, LRN after
conv1/conv2), written against the fluid layer API; grouped convolutions in
the original two-tower split are folded into full convs, matching the
reference benchmark config.
"""
from __future__ import annotations

from ..fluid import layers


def alexnet(img, class_dim=1000):
    """img: [-1, 3, 224, 224] -> logits [-1, class_dim]."""
    conv1 = layers.conv2d(
        input=img, num_filters=96, filter_size=11, stride=4, padding=1,
        act="relu",
    )
    norm1 = layers.lrn(input=conv1, n=5, alpha=1e-4, beta=0.75)
    pool1 = layers.pool2d(
        input=norm1, pool_size=3, pool_stride=2, pool_type="max")

    conv2 = layers.conv2d(
        input=pool1, num_filters=256, filter_size=5, padding=2, act="relu")
    norm2 = layers.lrn(input=conv2, n=5, alpha=1e-4, beta=0.75)
    pool2 = layers.pool2d(
        input=norm2, pool_size=3, pool_stride=2, pool_type="max")

    conv3 = layers.conv2d(
        input=pool2, num_filters=384, filter_size=3, padding=1, act="relu")
    conv4 = layers.conv2d(
        input=conv3, num_filters=384, filter_size=3, padding=1, act="relu")
    conv5 = layers.conv2d(
        input=conv4, num_filters=256, filter_size=3, padding=1, act="relu")
    pool5 = layers.pool2d(
        input=conv5, pool_size=3, pool_stride=2, pool_type="max")

    fc6 = layers.fc(input=pool5, size=4096, act="relu")
    drop6 = layers.dropout(x=fc6, dropout_prob=0.5)
    fc7 = layers.fc(input=drop6, size=4096, act="relu")
    drop7 = layers.dropout(x=fc7, dropout_prob=0.5)
    return layers.fc(input=drop7, size=class_dim)


def build_train(img, label, class_dim=1000):
    logits = alexnet(img, class_dim=class_dim)
    cost = layers.softmax_with_cross_entropy(logits=logits, label=label)
    avg_cost = layers.mean(cost)
    prediction = layers.softmax(logits)
    acc = layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction
