"""Transformer NMT (capability target: reference benchmark/fluid
machine_translation.py + test_parallel_executor.py:444 transformer config),
built from fluid layers with static shapes (XLA-friendly: fixed max_len,
padding masks instead of LoD).

This is the flagship model for multi-chip sharding: fc weights shard on the
hidden axis (tensor parallel), feeds on batch (data parallel) — see
paddle_tpu.parallel.plan_transformer_tp.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..fluid import layers
from ..fluid.initializer import NumpyArrayInitializer
from ..fluid.param_attr import ParamAttr


@dataclasses.dataclass
class TransformerConfig:
    src_vocab: int = 10000
    trg_vocab: int = 10000
    max_len: int = 64
    d_model: int = 256
    n_heads: int = 8
    d_ff: int = 1024
    n_layers: int = 2
    dropout: float = 0.1
    is_test: bool = False
    # sequence/context parallelism: attention runs as the fused
    # ring/ulysses op (layers.ring_attention) with the sequence dim sharded
    # over `sp_axis` of the ParallelExecutor mesh. Attention-prob dropout is
    # skipped in this mode (flash attention never materializes the probs).
    seq_parallel: bool = False
    sp_impl: str = "ring"
    sp_axis: str = "sp"
    # Megatron TP axis: the q/k/v projections are column-parallel under
    # plan_transformer_tp, so their [N,L,H,dh] reshape arrives with H
    # sharded over tp. The fused attention op must keep heads on that axis
    # inside its shard_map — otherwise GSPMD has to transpose two tiled
    # dims at the boundary and falls back to full rematerialization
    # (hybrid dp×tp×sp mesh, spmd_partitioner.cc:652).
    tp_axis: str = "tp"
    # activation rematerialization: wrap each encoder/decoder layer in a
    # layers.Recompute region (jax.checkpoint) — backward re-runs the
    # layer instead of storing its activations, the standard TPU lever
    # for fitting long sequences / deep stacks in HBM
    recompute: bool = False


def _pos_encoding_table(max_len, d_model):
    pos = np.arange(max_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    table = np.zeros((max_len, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


def _const_param(name, value):
    return layers.create_parameter(
        shape=list(value.shape), dtype="float32",
        attr=ParamAttr(name=name, initializer=NumpyArrayInitializer(value),
                       trainable=False),
    )


def _mha(cfg: TransformerConfig, q_in, kv_in, mask=None, causal=False,
         name=""):
    """Multi-head attention: fc projections on [N, L, D] (num_flatten_dims=2),
    batched 4D matmuls on the MXU. With cfg.seq_parallel, the score/softmax/
    context chain is replaced by the fused ring attention op (sequence dim
    sharded over the mesh's sp axis)."""
    d, h = cfg.d_model, cfg.n_heads
    dh = d // h

    def proj(x, pname):
        return layers.fc(
            input=x, size=d, num_flatten_dims=2, bias_attr=False,
            param_attr=ParamAttr(name=f"{name}.{pname}.w"),
        )

    if cfg.seq_parallel:
        if mask is not None:
            raise ValueError(
                "seq_parallel _mha only supports causal masking (the fused "
                "ring attention op takes no additive mask)"
            )
        q = layers.reshape(proj(q_in, "q"), shape=[0, 0, h, dh])
        k = layers.reshape(proj(kv_in, "k"), shape=[0, 0, h, dh])
        v = layers.reshape(proj(kv_in, "v"), shape=[0, 0, h, dh])
        ctx = layers.ring_attention(
            q, k, v, causal=causal, impl=cfg.sp_impl, seq_axis=cfg.sp_axis,
            head_axis=cfg.tp_axis,
        )  # [N, L, H, dh]
    else:
        def split_heads(x):
            r = layers.reshape(x, shape=[0, 0, h, dh])
            return layers.transpose(r, perm=[0, 2, 1, 3])  # [N, H, L, dh]

        q = split_heads(proj(q_in, "q"))
        k = split_heads(proj(kv_in, "k"))
        v = split_heads(proj(kv_in, "v"))

        scores = layers.matmul(q, k, transpose_y=True, alpha=dh ** -0.5)
        if mask is not None:
            scores = layers.elementwise_add(scores, mask)  # bcast [L,L] on tail
        weights = layers.softmax(scores)
        if cfg.dropout and not cfg.is_test:
            weights = layers.dropout(weights, dropout_prob=cfg.dropout,
                                     is_test=cfg.is_test)
        ctx = layers.matmul(weights, v)  # [N, H, L, dh]
        ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])  # [N, L, H, dh]
    ctx = layers.reshape(ctx, shape=[0, 0, d])
    return layers.fc(
        input=ctx, size=d, num_flatten_dims=2, bias_attr=False,
        param_attr=ParamAttr(name=f"{name}.out.w"),
    )


def _ffn(cfg: TransformerConfig, x, name=""):
    hidden = layers.fc(
        input=x, size=cfg.d_ff, num_flatten_dims=2, act="relu",
        param_attr=ParamAttr(name=f"{name}.ff1.w"),
    )
    if cfg.dropout and not cfg.is_test:
        hidden = layers.dropout(hidden, dropout_prob=cfg.dropout,
                                is_test=cfg.is_test)
    return layers.fc(
        input=hidden, size=cfg.d_model, num_flatten_dims=2,
        param_attr=ParamAttr(name=f"{name}.ff2.w"),
    )


def _residual_ln(x, sub, name=""):
    return layers.layer_norm(
        layers.elementwise_add(x, sub), begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{name}.ln.scale"),
        bias_attr=ParamAttr(name=f"{name}.ln.bias"),
    )


def _embed(cfg, ids, vocab, name):
    emb = layers.embedding(
        ids, size=[vocab, cfg.d_model],
        param_attr=ParamAttr(name=f"{name}.emb"),
    )
    pos = _const_param(f"{name}.pos_table",
                      _pos_encoding_table(cfg.max_len, cfg.d_model))
    x = layers.elementwise_add(emb, pos, axis=1)
    if cfg.dropout and not cfg.is_test:
        x = layers.dropout(x, dropout_prob=cfg.dropout, is_test=cfg.is_test)
    return x


def _maybe_recompute(cfg, layer_fn, x):
    """Wrap one transformer layer in a Recompute region when
    cfg.recompute (activation remat: backward re-runs the layer)."""
    if not cfg.recompute:
        return layer_fn(x)
    rc = layers.Recompute()
    with rc.block():
        out = layer_fn(x)
    return rc.output(out)


def encoder(cfg: TransformerConfig, src_ids):
    x = _embed(cfg, src_ids, cfg.src_vocab, "enc")
    for i in range(cfg.n_layers):
        def enc_layer(x, i=i):
            h = _residual_ln(x, _mha(cfg, x, x, name=f"enc{i}.self"),
                             name=f"enc{i}.a")
            return _residual_ln(h, _ffn(cfg, h, name=f"enc{i}"),
                                name=f"enc{i}.b")

        x = _maybe_recompute(cfg, enc_layer, x)
    return x


def decoder(cfg: TransformerConfig, trg_ids, enc_out):
    if cfg.seq_parallel:
        mask = None  # causal handled inside the ring attention op
    else:
        causal = np.triu(
            np.full((cfg.max_len, cfg.max_len), -1e9, dtype=np.float32), k=1
        )
        mask = _const_param("dec.causal_mask", causal)
    x = _embed(cfg, trg_ids, cfg.trg_vocab, "dec")
    for i in range(cfg.n_layers):
        def dec_layer(x, i=i):
            h = _residual_ln(x, _mha(cfg, x, x, mask=mask, causal=True,
                                     name=f"dec{i}.self"),
                             name=f"dec{i}.a")
            h = _residual_ln(h, _mha(cfg, h, enc_out, name=f"dec{i}.cross"),
                             name=f"dec{i}.b")
            return _residual_ln(h, _ffn(cfg, h, name=f"dec{i}"),
                                name=f"dec{i}.c")

        x = _maybe_recompute(cfg, dec_layer, x)
    return x


def build_train(cfg: TransformerConfig, src_ids, trg_ids, labels):
    """src_ids/trg_ids: [-1, max_len] int64; labels: [-1, max_len, 1] int64.
    Returns (avg_cost, logits)."""
    enc_out = encoder(cfg, src_ids)
    dec_out = decoder(cfg, trg_ids, enc_out)
    logits = layers.fc(
        input=dec_out, size=cfg.trg_vocab, num_flatten_dims=2,
        param_attr=ParamAttr(name="proj.w"),
    )
    cost = layers.softmax_with_cross_entropy(logits=logits, label=labels)
    avg_cost = layers.mean(cost)
    return avg_cost, logits
