"""GoogLeNet / Inception-v1 (reference benchmark/googlenet.py, legacy suite).

The reference's legacy-GPU table (benchmark/README.md:48-52) trains this at
bs=128 on a K40m; `benchmarks/legacy_conv_bench.py` reproduces the workload.

Standard Inception-v1: stem, 9 inception blocks with 1x1/3x3/5x5/pool-proj
branches concatenated on channels, global average pool, single classifier
head (the two auxiliary heads of the paper are omitted, as in the reference
benchmark config which trains the main head only).
"""
from __future__ import annotations

from ..fluid import layers


def _inception(x, c1, c3r, c3, c5r, c5, proj):
    b1 = layers.conv2d(input=x, num_filters=c1, filter_size=1, act="relu")
    b3 = layers.conv2d(input=x, num_filters=c3r, filter_size=1, act="relu")
    b3 = layers.conv2d(input=b3, num_filters=c3, filter_size=3, padding=1,
                       act="relu")
    b5 = layers.conv2d(input=x, num_filters=c5r, filter_size=1, act="relu")
    b5 = layers.conv2d(input=b5, num_filters=c5, filter_size=5, padding=2,
                       act="relu")
    bp = layers.pool2d(input=x, pool_size=3, pool_stride=1, pool_padding=1,
                       pool_type="max")
    bp = layers.conv2d(input=bp, num_filters=proj, filter_size=1, act="relu")
    return layers.concat([b1, b3, b5, bp], axis=1)


def googlenet(img, class_dim=1000):
    """img: [-1, 3, 224, 224] -> logits [-1, class_dim]."""
    x = layers.conv2d(input=img, num_filters=64, filter_size=7, stride=2,
                      padding=3, act="relu")
    x = layers.pool2d(input=x, pool_size=3, pool_stride=2, pool_type="max")
    x = layers.conv2d(input=x, num_filters=64, filter_size=1, act="relu")
    x = layers.conv2d(input=x, num_filters=192, filter_size=3, padding=1,
                      act="relu")
    x = layers.pool2d(input=x, pool_size=3, pool_stride=2, pool_type="max")

    x = _inception(x, 64, 96, 128, 16, 32, 32)      # 3a
    x = _inception(x, 128, 128, 192, 32, 96, 64)    # 3b
    x = layers.pool2d(input=x, pool_size=3, pool_stride=2, pool_type="max")

    x = _inception(x, 192, 96, 208, 16, 48, 64)     # 4a
    x = _inception(x, 160, 112, 224, 24, 64, 64)    # 4b
    x = _inception(x, 128, 128, 256, 24, 64, 64)    # 4c
    x = _inception(x, 112, 144, 288, 32, 64, 64)    # 4d
    x = _inception(x, 256, 160, 320, 32, 128, 128)  # 4e
    x = layers.pool2d(input=x, pool_size=3, pool_stride=2, pool_type="max")

    x = _inception(x, 256, 160, 320, 32, 128, 128)  # 5a
    x = _inception(x, 384, 192, 384, 48, 128, 128)  # 5b
    x = layers.pool2d(input=x, pool_type="avg", global_pooling=True)
    x = layers.dropout(x=x, dropout_prob=0.4)
    return layers.fc(input=x, size=class_dim)


def build_train(img, label, class_dim=1000):
    logits = googlenet(img, class_dim=class_dim)
    cost = layers.softmax_with_cross_entropy(logits=logits, label=label)
    avg_cost = layers.mean(cost)
    prediction = layers.softmax(logits)
    acc = layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction
