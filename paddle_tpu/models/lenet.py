"""MNIST LeNet-5-style convnet (reference benchmark/fluid/mnist.py cnn_model)."""
from __future__ import annotations

from ..fluid import layers, nets


def build(img, label):
    """img: [-1, 1, 28, 28], label: [-1, 1] int64.
    Returns (avg_cost, accuracy, prediction)."""
    conv1 = nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2, pool_stride=2,
        act="relu",
    )
    conv2 = nets.simple_img_conv_pool(
        input=conv1, filter_size=5, num_filters=50, pool_size=2, pool_stride=2,
        act="relu",
    )
    fc1 = layers.fc(input=conv2, size=500, act="relu")
    logits = layers.fc(input=fc1, size=10)
    cost = layers.softmax_with_cross_entropy(logits=logits, label=label)
    avg_cost = layers.mean(cost)
    prediction = layers.softmax(logits)
    acc = layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction
