"""Stacked LSTM text classifier (reference benchmark/fluid/
stacked_dynamic_lstm.py: embedding -> N x [fc -> dynamic_lstm] -> pools ->
fc softmax)."""
from __future__ import annotations

from ..fluid import layers


def build(data, label, dict_dim, emb_dim=512, hid_dim=512, stacked_num=3,
          class_dim=2):
    """data: int64 ids [N, T] (lod_level=1 padded+lengths), label: [N, 1].
    Returns (avg_cost, accuracy, prediction)."""
    emb = layers.embedding(input=data, size=[dict_dim, emb_dim])

    fc1 = layers.fc(input=emb, size=hid_dim, num_flatten_dims=2)
    lstm1, cell1 = layers.dynamic_lstm(input=fc1, size=hid_dim,
                                       use_peepholes=False)

    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = layers.fc(input=inputs, size=hid_dim, num_flatten_dims=2)
        lstm, cell = layers.dynamic_lstm(
            input=fc, size=hid_dim, is_reverse=False, use_peepholes=False
        )
        inputs = [fc, lstm]

    fc_last = layers.sequence_pool(input=inputs[0], pool_type="max")
    lstm_last = layers.sequence_pool(input=inputs[1], pool_type="max")

    logits = layers.fc(input=[fc_last, lstm_last], size=class_dim)
    cost = layers.softmax_with_cross_entropy(logits=logits, label=label)
    avg_cost = layers.mean(cost)
    prediction = layers.softmax(logits)
    acc = layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction
