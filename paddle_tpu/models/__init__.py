"""Model zoo — the workloads the reference benchmarks/book tests run
(reference benchmark/fluid/{mnist,resnet,vgg,stacked_dynamic_lstm,
machine_translation}.py), built on the paddle_tpu.fluid layer API."""
from . import lenet, resnet, transformer, vgg  # noqa: F401
