"""Model zoo — the workloads the reference benchmarks/book tests run
(reference benchmark/fluid/{mnist,resnet,vgg,stacked_dynamic_lstm,
machine_translation}.py plus the legacy benchmark/{alexnet,googlenet,
smallnet_mnist_cifar}.py suite), built on the paddle_tpu.fluid layer API."""
from . import (  # noqa: F401
    alexnet,
    googlenet,
    lenet,
    resnet,
    smallnet,
    transformer,
    vgg,
)
