"""SmallNet — the Caffe `cifar10_quick` convnet the reference benchmarks as
"SmallNet" (reference benchmark/smallnet_mnist_cifar.py; table at
benchmark/README.md:56-61, bs=128 on a K40m).

3 conv/pool stages + 2 fc; cifar-scale [3, 32, 32] input.
"""
from __future__ import annotations

from ..fluid import layers


def smallnet(img, class_dim=10):
    """img: [-1, 3, 32, 32] -> logits [-1, class_dim]."""
    x = layers.conv2d(input=img, num_filters=32, filter_size=5, padding=2)
    x = layers.pool2d(input=x, pool_size=3, pool_stride=2, pool_type="max")
    x = layers.relu(x)
    x = layers.conv2d(input=x, num_filters=32, filter_size=5, padding=2,
                      act="relu")
    x = layers.pool2d(input=x, pool_size=3, pool_stride=2, pool_type="avg")
    x = layers.conv2d(input=x, num_filters=64, filter_size=5, padding=2,
                      act="relu")
    x = layers.pool2d(input=x, pool_size=3, pool_stride=2, pool_type="avg")
    x = layers.fc(input=x, size=64)
    return layers.fc(input=x, size=class_dim)


def build_train(img, label, class_dim=10):
    logits = smallnet(img, class_dim=class_dim)
    cost = layers.softmax_with_cross_entropy(logits=logits, label=label)
    avg_cost = layers.mean(cost)
    prediction = layers.softmax(logits)
    acc = layers.accuracy(input=prediction, label=label)
    return avg_cost, acc, prediction
