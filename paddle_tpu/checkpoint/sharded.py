"""Sharded checkpoints (ISSUE 15 / ROADMAP checkpoint residual #2):
one payload file PER MESH SHARD with a merged manifest.

When a sharded SPMD export makes a single decoder (or training state)
span chips, a one-payload checkpoint forces every host to serialize the
whole model through one writer. This layout keeps the manifest MERGED
(one ``manifest.json`` indexing everything — the inspect/verify story
stays one file) while the bytes split into ``segments-<nonce>.s<K>.bin``
per shard along a designated mesh axis:

  - a tensor whose rule shards dim ``d`` over ``shard_axis`` splits
    into S equal slices along ``d``; slice k lives in shard file k
    (each slice carries its own crc32 — a corrupt shard names the
    tensor AND the shard file);
  - a tensor the rules replicate (or whose dim doesn't divide) is
    written ONCE into shard file 0 and marked replicated — loads hand
    it to every shard;
  - COMMIT is the same torn-write discipline as ``format.py``: all
    payloads written + fsynced under fresh nonces, tmp manifest
    fsynced, the ``checkpoint.save`` fault site, one atomic
    ``os.replace`` — a crash anywhere leaves the previous checkpoint
    fully loadable, orphans swept by the next successful commit;
  - LOADS either REASSEMBLE (``shard=None`` — slices verified then
    stitched; the full-tree view ``load_decoder_checkpoint`` consumes)
    or load PER SHARD (``shard=k`` — only shard k's file plus the
    replicated tensors are read/verified, the per-host fast path).

``format.load_checkpoint_arrays`` delegates here when a manifest
declares ``payloads`` (plural), so every existing consumer — decoder
deploys, ``checkpoint inspect``/``verify`` — reads sharded checkpoints
transparently.
"""
from __future__ import annotations

import json
import os
import threading
import uuid
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..distributed import faults as _faults
from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger
from . import format as _fmt
from .format import (CheckpointCorruptError, CheckpointError,
                     MANIFEST_NAME, FORMAT_VERSION)

__all__ = ["save_sharded_checkpoint", "load_sharded_checkpoint",
           "load_sharded_arrays", "is_sharded_manifest"]

_log = get_logger("checkpoint")

_m_saves = _metrics.counter("checkpoint.saves")
_m_loads = _metrics.counter("checkpoint.loads")
_m_bytes_written = _metrics.counter("checkpoint.bytes_written")
_m_bytes_read = _metrics.counter("checkpoint.bytes_read")
_m_corrupt = _metrics.counter("checkpoint.corrupt")


def is_sharded_manifest(manifest: Dict[str, Any]) -> bool:
    return "payloads" in manifest


def _shard_dim(spec_entry, shard_axis: str):
    """Index of the first spec dim carrying ``shard_axis`` (None when
    the tensor replicates over it)."""
    for d, e in enumerate(spec_entry):
        if e is None:
            continue
        axes = e if isinstance(e, (tuple, list)) else (e,)
        if shard_axis in (str(a) for a in axes):
            return d
    return None


def save_sharded_checkpoint(dirname: str, tree, *, shard_axis: str,
                            mesh_spec, rules,
                            meta: Optional[Dict[str, Any]] = None) -> str:
    """Write ``tree`` as a sharded checkpoint: S = the mesh's
    ``shard_axis`` size payload files + one merged manifest. The mesh
    spec and rules ride the manifest meta (``meta['mesh']``) so a
    loader deploys the EXACT layout the exporter trained/served —
    sharding travels with the artifact, not in the operator's head."""
    from ..mesh import MeshSpec, ShardingRules

    mesh_spec = MeshSpec.coerce(mesh_spec)
    rules = ShardingRules.coerce(rules)
    nshards = mesh_spec.axis_size(shard_axis)  # KeyError -> caller bug
    flat, skel = _fmt._flatten(tree)
    meta = dict(meta or {})
    meta["mesh"] = {"spec": mesh_spec.to_dict(),
                    "rules": rules.to_dict(),
                    "shard_axis": str(shard_axis)}

    os.makedirs(dirname, exist_ok=True)
    nonce = uuid.uuid4().hex[:12]
    payload_names = [f"segments-{nonce}.s{k}.bin" for k in range(nshards)]
    tensors: List[Dict[str, Any]] = []
    written = 0
    # lint: allow-blocking — commits serialize by design (format.py's
    # _commit_mu); file I/O dominates, contention is rare
    with _fmt._commit_mu, _tracing.span(
            "checkpoint.save", dir=dirname, tensors=len(flat),
            shards=nshards):
        files = [open(os.path.join(dirname, n), "wb")
                 for n in payload_names]
        offs = [0] * nshards
        try:
            for name, arr in flat.items():
                arr = np.ascontiguousarray(arr)
                spec = rules.spec_for(name, arr.ndim)
                dim = _shard_dim(tuple(spec), shard_axis)
                if dim is not None and (dim >= arr.ndim
                                        or arr.shape[dim] % nshards):
                    dim = None  # indivisible -> replicated, like the
                    # executor's best-effort discipline
                entry: Dict[str, Any] = {
                    "name": name,
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                }
                segs = []
                if dim is None:
                    pieces = [(0, arr)]
                else:
                    entry["dim"] = int(dim)
                    pieces = [(k, s) for k, s in enumerate(
                        np.split(arr, nshards, axis=dim))]
                for k, piece in pieces:
                    raw = np.ascontiguousarray(piece).tobytes()
                    pad = (-offs[k]) % _fmt._ALIGN
                    if pad:
                        files[k].write(b"\0" * pad)
                        offs[k] += pad
                    files[k].write(raw)
                    segs.append({"shard": k, "offset": offs[k],
                                 "nbytes": len(raw),
                                 "crc32": zlib.crc32(raw) & 0xFFFFFFFF})
                    offs[k] += len(raw)
                    written += len(raw)
                entry["segments"] = segs
                tensors.append(entry)
            for f in files:
                f.flush()
                os.fsync(f.fileno())
        finally:
            for f in files:
                f.close()
        manifest = {
            "format": FORMAT_VERSION,
            "payloads": payload_names,
            "shards": nshards,
            "shard_axis": str(shard_axis),
            "meta": meta,
            "tensors": tensors,
            "tree": skel,
        }
        tmp = os.path.join(
            dirname,
            f"{MANIFEST_NAME}.tmp.{os.getpid()}.{threading.get_ident()}")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        _faults.fire("checkpoint.save")
        os.replace(tmp, os.path.join(dirname, MANIFEST_NAME))
        keep = set(payload_names)
        for n in os.listdir(dirname):
            stale = ((n.startswith("segments-") and n.endswith(".bin")
                      and n not in keep)
                     or n.startswith(f"{MANIFEST_NAME}.tmp."))
            if stale:
                try:
                    os.remove(os.path.join(dirname, n))
                except OSError:  # pragma: no cover - racing GC is fine
                    pass
    _m_saves.inc()
    _m_bytes_written.inc(written)
    _log.info("sharded checkpoint committed: %s (%d tensors, %d shards, "
              "%d bytes)", dirname, len(tensors), nshards, written)
    return os.path.join(dirname, MANIFEST_NAME)


class _MissingPayload(CheckpointError):
    """Internal: a referenced shard file is gone — possibly a stale
    manifest racing a concurrent cross-process commit's GC (the
    monolithic loader's re-read-once recovery applies here too)."""


def _read_segment(maps, dirname, manifest, t, seg, verify: bool
                  ) -> np.ndarray:
    """One verified slice out of its shard's map (zero-copy view) —
    bounds/crc/shape checks via the shared ``format.verified_segment``
    rule."""
    name = str(t["name"])
    k = int(seg["shard"])
    if k not in maps:
        path = os.path.join(dirname, manifest["payloads"][k])
        if not os.path.exists(path):
            raise _MissingPayload(
                f"manifest references missing shard payload '{path}' — "
                "the checkpoint directory was partially deleted")
        maps[k] = _fmt.open_payload_map(path) + (path,)
    mm, size, path = maps[k]
    shape = [int(s) for s in t["shape"]]
    dim = t.get("dim")
    if dim is not None:
        shape[int(dim)] //= int(manifest["shards"])
    return _fmt.verified_segment(
        mm, size, path, name, int(seg["offset"]), int(seg["nbytes"]),
        str(t["dtype"]), shape, int(seg["crc32"]), verify,
        where=f" in shard {k}")


def load_sharded_arrays(dirname: str, shard: Optional[int] = None,
                        verify: bool = True, _manifest=None,
                        _retried: bool = False
                        ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Flat ``{name: array}`` view of a sharded checkpoint.

    ``shard=None`` REASSEMBLES global tensors (slices verified, then
    concatenated along the recorded dim — reassembly copies; replicated
    tensors stay zero-copy views). ``shard=k`` loads shard k's LOCAL
    slices (plus replicated tensors) touching only shard files 0 and k
    — the per-host path. ``_manifest`` lets ``load_checkpoint_arrays``
    hand over the manifest it already read instead of re-parsing it."""
    manifest = _manifest if _manifest is not None \
        else _fmt.read_manifest(dirname)
    if not is_sharded_manifest(manifest):
        raise CheckpointError(
            f"'{dirname}' is not a sharded checkpoint — use "
            "load_checkpoint_arrays")
    nshards = int(manifest["shards"])
    if shard is not None and not (0 <= int(shard) < nshards):
        raise CheckpointError(
            f"shard {shard} out of range: '{dirname}' has {nshards} "
            "shards")
    try:
        return _load_sharded_body(dirname, manifest, nshards, shard,
                                  verify)
    except _MissingPayload:
        if _retried:
            raise
        # a CONCURRENT cross-process save may have committed between
        # our manifest read and the payload open — its GC unlinks the
        # files our (now stale) manifest references. Re-read once: a
        # fresh manifest naming DIFFERENT payloads means the directory
        # is healthy and simply moved on (same recovery as the
        # monolithic loader); the same payloads still missing means
        # they really were deleted out from under the manifest.
        fresh = _fmt.read_manifest(dirname)
        if not is_sharded_manifest(fresh):
            # the overwriting save switched the directory to the
            # MONOLITHIC layout: a whole-tree read simply follows it;
            # a shard-k read cannot be satisfied there — that layout
            # change is worth a typed error, not a silent full load
            if shard is None:
                return _fmt.load_checkpoint_arrays(dirname,
                                                   verify=verify)
            raise CheckpointError(
                f"'{dirname}' was overwritten with a MONOLITHIC "
                f"checkpoint while loading shard {shard} — per-shard "
                "loads need the sharded layout") from None
        if fresh["payloads"] == manifest["payloads"]:
            raise
        return load_sharded_arrays(dirname, shard=shard, verify=verify,
                                   _manifest=fresh, _retried=True)


def _load_sharded_body(dirname, manifest, nshards, shard, verify):
    maps: Dict[int, Any] = {}
    out: Dict[str, np.ndarray] = {}
    read = 0
    with _tracing.span("checkpoint.load", dir=dirname,
                       tensors=len(manifest["tensors"]),
                       shards=nshards):
        for t in manifest["tensors"]:
            name = str(t["name"])
            segs = t["segments"]
            if t.get("dim") is None:
                arr = _read_segment(maps, dirname, manifest, t, segs[0],
                                    verify)
                read += int(segs[0]["nbytes"])
            elif shard is not None:
                seg = next((s for s in segs
                            if int(s["shard"]) == int(shard)), None)
                if seg is None:
                    _m_corrupt.inc()
                    raise CheckpointCorruptError(
                        f"tensor '{name}' has no slice for shard "
                        f"{shard} in '{dirname}'", tensor=name)
                arr = _read_segment(maps, dirname, manifest, t, seg,
                                    verify)
                read += int(seg["nbytes"])
            else:
                slices = []
                for seg in sorted(segs, key=lambda s: int(s["shard"])):
                    slices.append(_read_segment(maps, dirname, manifest,
                                                t, seg, verify))
                    read += int(seg["nbytes"])
                if len(slices) != nshards:
                    _m_corrupt.inc()
                    raise CheckpointCorruptError(
                        f"tensor '{name}' has {len(slices)} slices, "
                        f"manifest declares {nshards} shards",
                        tensor=name)
                arr = np.concatenate(slices, axis=int(t["dim"]))
            out[name] = arr
    _m_loads.inc()
    _m_bytes_read.inc(read)
    return out, manifest


def load_sharded_checkpoint(dirname: str, shard: Optional[int] = None,
                            verify: bool = True
                            ) -> Tuple[Any, Dict[str, Any]]:
    """Tree view (containers restored). ``shard=None`` -> the global
    tree; ``shard=k`` -> shard k's local tree, sharded tensors sliced
    along their recorded dim."""
    arrays, manifest = load_sharded_arrays(dirname, shard=shard,
                                           verify=verify)
    return _fmt.restore_tree(arrays, manifest), manifest
