"""Versioned checkpoint format: one JSON manifest + one raw-segment
payload file (ISSUE 12).

The reference framework's whole persistence story is "programs and
parameters are artifacts" (`fluid/io.py` save/load discipline); this
module is the parameter half done properly for serving-scale tensors:

  - the PAYLOAD (``segments-<nonce>.bin``) is every tensor's raw bytes
    back to back, 64-byte aligned, written once and never modified;
  - the MANIFEST (``manifest.json``) indexes it: per tensor the dtype,
    shape, byte offset, byte length, and a crc32 — plus the nested
    container skeleton (dict/tuple/list) the flat names were flattened
    from, and a caller ``meta`` dict (a decoder checkpoint stores its
    ``DecoderSpec`` there);
  - COMMIT is the manifest rename: payloads carry a fresh nonce per
    save and the manifest is written tmp + fsync + atomic
    ``os.replace`` (the ``master.snapshot``/``TuningCache`` torn-write
    discipline). A crash anywhere before the rename — the
    ``checkpoint.save`` fault site sits right there — leaves the
    previous manifest pointing at the previous payload, both intact;
    orphaned payloads from crashed saves are garbage-collected by the
    next successful commit;
  - LOADS are chunked-verified, zero-copy: the payload is mmap'd
    read-only, each segment's crc32 is folded in bounded chunks (no
    whole-file read), and the returned arrays are non-writeable views
    straight over the map — the same receive-side discipline as
    ``rpc.from_wire(copy=False)``. A truncated or bit-flipped segment
    fails with a typed error NAMING the tensor, not a shape error
    three layers into the model.
"""
from __future__ import annotations

import json
import mmap
import os
import threading
import uuid
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..distributed import faults as _faults
from ..observability import metrics as _metrics, tracing as _tracing
from ..observability.log import get_logger

__all__ = ["CheckpointError", "CheckpointCorruptError", "CheckpointWriter",
           "save_checkpoint_tree", "load_checkpoint_tree",
           "load_checkpoint_arrays", "read_manifest", "MANIFEST_NAME",
           "FORMAT_VERSION"]

_log = get_logger("checkpoint")

MANIFEST_NAME = "manifest.json"
FORMAT_VERSION = 1
# segment alignment inside the payload: mmap-view friendly for every
# numeric dtype, and matches the allocator granularity most filesystems
# round to anyway
_ALIGN = 64
# crc folding chunk: verification touches the map in bounded pieces so
# a multi-GiB tensor never needs a contiguous read buffer
_CRC_CHUNK = 1 << 20

_m_saves = _metrics.counter("checkpoint.saves")
_m_loads = _metrics.counter("checkpoint.loads")
_m_bytes_written = _metrics.counter("checkpoint.bytes_written")
_m_bytes_read = _metrics.counter("checkpoint.bytes_read")
# verification failures (crc mismatch / truncation) — the counter a
# fleet operator alerts on: a nonzero value means storage corrupted a
# deployed artifact
_m_corrupt = _metrics.counter("checkpoint.corrupt")
# incremental/delta checkpoints (ISSUE 13): tensors a delta save
# REFERENCED from its base (identical crc32) instead of rewriting —
# the rollout loop's save cost becomes proportional to what changed
_m_delta_skipped = _metrics.counter("checkpoint.delta_skipped")

# serializes whole commits (payload write -> manifest rename -> orphan
# GC) within this process, the TuningCache._flush_mu discipline:
# without it, committer A's GC could delete committer B's fully-written
# but not-yet-referenced nonce payload (or its tmp manifest), leaving
# B's manifest pointing at nothing. CROSS-process writers to one
# directory are the caller's exclusion problem — same contract as every
# one-writer artifact in this repo (master.snapshot, save_checkpoint).
_commit_mu = threading.Lock()


class CheckpointError(IOError):
    """A checkpoint artifact is missing, unreadable, or structurally
    wrong (bad format version, unknown tensor set). Typed so serving
    deploy paths surface it as-is instead of a deep KeyError."""


class CheckpointCorruptError(CheckpointError):
    """A specific tensor's bytes failed verification (crc mismatch or
    truncation). Carries ``tensor`` — the load path names the victim
    instead of letting a garbled weight surface as a shape error three
    layers into the model."""

    def __init__(self, msg: str, tensor: Optional[str] = None):
        super().__init__(msg)
        self.tensor = tensor


# --- tree flatten / unflatten -------------------------------------------

def _flatten(tree, prefix: str = "", out: Optional[OrderedDict] = None):
    """Flatten a nested dict/tuple/list parameter tree into
    ``{"a/b/0": ndarray}`` plus a JSON-able skeleton that remembers the
    container types (tuples restore as tuples — the decoder contract's
    ``(gamma, beta)`` layer-norm pairs)."""
    if out is None:
        out = OrderedDict()
    if isinstance(tree, dict):
        skel = {}
        for k in tree:
            k = str(k)
            if "/" in k:
                raise CheckpointError(
                    f"tree key {k!r} contains '/', the flatten separator")
            _, skel[k] = _flatten(tree[k], f"{prefix}{k}/", out)
        return out, {"d": skel}
    if isinstance(tree, (tuple, list)):
        skels = []
        for i, v in enumerate(tree):
            _, s = _flatten(v, f"{prefix}{i}/", out)
            skels.append(s)
        return out, {("t" if isinstance(tree, tuple) else "l"): skels}
    # leaf: anything numpy can view as an n-d array of a plain dtype
    arr = np.asarray(tree)
    if arr.dtype == object:
        raise CheckpointError(
            f"tensor '{prefix[:-1]}' has object dtype — checkpoints "
            "hold raw numeric segments only")
    name = prefix[:-1]
    out[name] = arr
    return out, name


def _unflatten(skel, arrays: Dict[str, Any]):
    if isinstance(skel, str):
        return arrays[skel]
    if "d" in skel:
        return {k: _unflatten(v, arrays) for k, v in skel["d"].items()}
    if "t" in skel:
        return tuple(_unflatten(v, arrays) for v in skel["t"])
    if "l" in skel:
        return [_unflatten(v, arrays) for v in skel["l"]]
    raise CheckpointError(f"malformed manifest tree node {skel!r}")


# --- writer -------------------------------------------------------------

class CheckpointWriter:
    """Staged, atomically-committed checkpoint writer.

    ``add()`` stages tensors (thread-safe — a sharded exporter may
    stage from several producer threads); ``commit()`` writes the
    payload + manifest with the torn-write discipline and returns the
    manifest path. A writer commits SUCCESSFULLY at most once — a
    commit that failed (ENOSPC, injected crash) leaves the staged
    tensors intact and may simply be retried.

    ``base`` (ISSUE 13, incremental checkpoints) points at an existing
    checkpoint DIRECTORY: tensors whose crc32 (and dtype/shape) equal
    the base's are not rewritten — their manifest entries carry
    ``"base": true`` and loads follow the recorded base chain. The
    crc32 index the format already keeps is exactly the change
    detector. A delta must live in its own directory (committing into
    the base's would garbage-collect the payload it references).
    """

    def __init__(self, dirname: str, meta: Optional[Dict[str, Any]] = None,
                 base: Optional[str] = None):
        self._dirname = str(dirname)
        self._meta = dict(meta or {})
        self._base = None if base is None else str(base)
        if self._base is not None:
            if os.path.realpath(self._base) == \
                    os.path.realpath(self._dirname):
                raise CheckpointError(
                    "a delta checkpoint cannot use its own directory "
                    "as its base — the commit's orphan sweep would "
                    "delete the payload it references")
            # fail early, typed: a bad base is a caller error at SAVE
            # time, not a mystery at some future load
            read_manifest(self._base)
        self._mu = threading.Lock()
        self._staged: "OrderedDict[str, np.ndarray]" = \
            OrderedDict()  # guarded-by: _mu
        self._tree_skel: Any = None  # guarded-by: _mu
        self._committed = False  # guarded-by: _mu
        self._committing = False  # guarded-by: _mu

    def add(self, name: str, array) -> None:
        """Stage one tensor under a flat name."""
        arr = np.ascontiguousarray(np.asarray(array))
        if arr.dtype == object:
            raise CheckpointError(
                f"tensor '{name}' has object dtype — checkpoints hold "
                "raw numeric segments only")
        with self._mu:
            if self._committed:
                raise CheckpointError("writer already committed")
            self._staged[str(name)] = arr

    def add_tree(self, tree) -> None:
        """Stage a whole nested parameter tree (dict/tuple/list of
        arrays); the container skeleton is recorded in the manifest so
        ``load_checkpoint_tree`` restores the exact structure."""
        flat, skel = _flatten(tree)
        with self._mu:
            if self._committed:
                raise CheckpointError("writer already committed")
            for k, v in flat.items():
                self._staged[k] = np.ascontiguousarray(v)
            self._tree_skel = skel

    def commit(self) -> str:
        """Write payload + manifest atomically; returns the manifest
        path. The ``checkpoint.save`` fault site fires between the
        fsynced tmp manifest and the committing rename — a crash there
        (chaos-tested) leaves the PREVIOUS checkpoint fully intact."""
        with self._mu:
            if self._committed:
                raise CheckpointError("writer already committed")
            if self._committing:
                raise CheckpointError("commit already in progress")
            self._committing = True
            staged = list(self._staged.items())
            skel = self._tree_skel
        try:
            if not staged:
                raise CheckpointError("nothing staged — empty checkpoint")
            dirname, meta = self._dirname, self._meta
            os.makedirs(dirname, exist_ok=True)
            # lint: allow-blocking — commits serialize by design (see
            # _commit_mu above); file I/O dominates, contention is rare
            with _commit_mu:
                path = self._commit_locked(dirname, meta, staged, skel)
        except BaseException:
            # a FAILED commit (ENOSPC, crash-site fault, ...) must not
            # poison the writer: nothing reached the manifest rename,
            # the staged tensors are intact, and a retry after the
            # operator clears the condition is the whole point of the
            # torn-write discipline.
            # Not a lost-update: only the thread that WON the first
            # section (set _committing) can reach these writes, so the
            # released-lock window has no competing writer by
            # construction.
            # lint: allow-unguarded(_committing)
            with self._mu:
                self._committing = False
            raise
        # same single-winner argument as the failure arm above
        # lint: allow-unguarded(_committed, _committing)
        with self._mu:
            self._committed = True
            self._committing = False
        return path

    def _base_index(self) -> Dict[str, Dict[str, Any]]:
        """name -> resolved (dtype/shape/crc32) entries of the base
        manifest. Base-ref entries in a delta base carry the resolved
        crc too, so delta-of-delta chains index without I/O."""
        manifest = read_manifest(self._base)
        return {str(t["name"]): t for t in manifest["tensors"]}

    def _commit_locked(self, dirname, meta, staged, skel) -> str:
        nonce = uuid.uuid4().hex[:12]
        payload_name = f"segments-{nonce}.bin"
        payload_path = os.path.join(dirname, payload_name)
        tensors: List[Dict[str, Any]] = []
        written = 0
        skipped = 0
        base_idx = self._base_index() if self._base is not None else {}
        with _tracing.span("checkpoint.save", dir=dirname,
                           tensors=len(staged)):
            # the payload's name is nonce-fresh and nothing references
            # it until the manifest rename lands, so it can be written
            # in place: a crash mid-write leaves an orphan the next
            # successful commit sweeps
            with open(payload_path, "wb") as f:
                off = 0
                for name, arr in staged:
                    raw = arr.tobytes()
                    crc = zlib.crc32(raw) & 0xFFFFFFFF
                    base_t = base_idx.get(name)
                    if base_t is not None and \
                            int(base_t["crc32"]) == crc and \
                            str(base_t["dtype"]) == str(arr.dtype) and \
                            list(base_t["shape"]) == list(arr.shape):
                        # unchanged since the base: reference, don't
                        # rewrite (the entry keeps the resolved crc/
                        # dtype/shape so chained deltas and loads can
                        # verify without touching the base first)
                        tensors.append({
                            "name": name,
                            "dtype": str(arr.dtype),
                            "shape": list(arr.shape),
                            "nbytes": len(raw),
                            "crc32": crc,
                            "base": True,
                        })
                        skipped += 1
                        continue
                    pad = (-off) % _ALIGN
                    if pad:
                        f.write(b"\0" * pad)
                        off += pad
                    f.write(raw)
                    tensors.append({
                        "name": name,
                        "dtype": str(arr.dtype),
                        "shape": list(arr.shape),
                        "offset": off,
                        "nbytes": len(raw),
                        "crc32": crc,
                    })
                    off += len(raw)
                    written += len(raw)
                f.flush()
                os.fsync(f.fileno())
            manifest = {
                "format": FORMAT_VERSION,
                "payload": payload_name,
                "meta": meta,
                "tensors": tensors,
            }
            if self._base is not None:
                # relative when possible: a checkpoint tree that moves
                # as a unit keeps working
                base_abs = os.path.abspath(self._base)
                try:
                    rel = os.path.relpath(base_abs,
                                          os.path.abspath(dirname))
                except ValueError:  # pragma: no cover - drive split
                    rel = base_abs
                manifest["base"] = rel
            if skel is not None:
                manifest["tree"] = skel
            # unique tmp per writer: a crashed commit's abandoned tmp
            # never collides with a retry's
            tmp = os.path.join(
                dirname,
                f"{MANIFEST_NAME}.tmp.{os.getpid()}.{threading.get_ident()}")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            _faults.fire("checkpoint.save")
            os.replace(tmp, os.path.join(dirname, MANIFEST_NAME))
            self._gc(dirname, payload_name)
        _m_saves.inc()
        _m_bytes_written.inc(written)
        if skipped:
            _m_delta_skipped.inc(skipped)
        _log.info("checkpoint committed: %s (%d tensors, %d bytes"
                  "%s)", dirname, len(tensors), written,
                  f", {skipped} unchanged via base" if skipped else "")
        return os.path.join(dirname, MANIFEST_NAME)

    @staticmethod
    def _gc(dirname: str, keep_payload: str) -> None:
        """Sweep payloads/tmp manifests that crashed saves abandoned —
        only after OUR manifest committed, so a concurrent reader of
        the previous checkpoint never loses its payload mid-load within
        the same save that replaces it (readers mmap before the GC of
        the NEXT save can touch their file)."""
        for n in os.listdir(dirname):
            stale = ((n.startswith("segments-") and n.endswith(".bin")
                      and n != keep_payload)
                     or n.startswith(f"{MANIFEST_NAME}.tmp."))
            if stale:
                try:
                    os.remove(os.path.join(dirname, n))
                except OSError:  # pragma: no cover - racing GC is fine
                    pass


def save_checkpoint_tree(dirname: str, tree,
                         meta: Optional[Dict[str, Any]] = None,
                         base: Optional[str] = None) -> str:
    """One-shot: flatten + stage + commit a nested parameter tree.
    ``base`` makes it a delta save (only changed tensors written)."""
    w = CheckpointWriter(dirname, meta=meta, base=base)
    w.add_tree(tree)
    return w.commit()


# --- reader -------------------------------------------------------------

def open_payload_map(path: str):
    """mmap a payload file read-only; returns ``(map, size)``. The map
    holds its own file reference. Missing file is the caller's
    stale-manifest concern — this helper assumes existence."""
    size = os.path.getsize(path)
    f = open(path, "rb")
    try:
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) \
            if size else b""
    finally:
        f.close()
    return mm, size


def verified_segment(mm, size: int, path: str, name: str, off: int,
                     nbytes: int, dtype_str: str, shape, crc32: int,
                     verify: bool, where: str = "") -> np.ndarray:
    """ONE payload segment as a zero-copy read-only view: bounds check,
    chunked crc32 fold, nbytes-vs-declared-shape check — every failure
    is ``CheckpointCorruptError`` NAMING the tensor (``where`` adds
    shard context). The one segment-verification rule both the
    monolithic and the sharded loader use, so a fix lands once."""
    if off < 0 or off + nbytes > size:
        _m_corrupt.inc()
        raise CheckpointCorruptError(
            f"tensor '{name}' is truncated{where}: segment "
            f"[{off}, {off + nbytes}) exceeds payload size "
            f"{size} ('{path}')", tensor=name)
    if verify:
        crc = 0
        for c0 in range(off, off + nbytes, _CRC_CHUNK):
            c1 = min(c0 + _CRC_CHUNK, off + nbytes)
            crc = zlib.crc32(mm[c0:c1], crc)
        if (crc & 0xFFFFFFFF) != int(crc32):
            _m_corrupt.inc()
            raise CheckpointCorruptError(
                f"tensor '{name}' failed its checksum{where} "
                f"(crc {crc & 0xFFFFFFFF:#010x} != manifest "
                f"{int(crc32):#010x}) — '{path}' is corrupt",
                tensor=name)
    dtype = np.dtype(str(dtype_str))
    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if count * dtype.itemsize != nbytes:
        _m_corrupt.inc()
        raise CheckpointCorruptError(
            f"tensor '{name}' declares shape {list(shape)} "
            f"({count} x {dtype}) but {nbytes} payload bytes{where}",
            tensor=name)
    return np.frombuffer(mm, dtype=dtype, count=count,
                         offset=off).reshape(shape)


def restore_tree(arrays: Dict[str, np.ndarray], manifest: Dict[str, Any]):
    """Rebuild the nested container tree a manifest's ``tree`` skeleton
    describes over a flat array map (shared by the monolithic and
    sharded tree loaders)."""
    skel = manifest.get("tree")
    if skel is None:
        return dict(arrays)
    try:
        return _unflatten(skel, arrays)
    except KeyError as e:
        raise CheckpointError(
            f"manifest tree references tensor {e.args[0]!r} that the "
            "tensor index does not declare") from e


def read_manifest(dirname: str) -> Dict[str, Any]:
    """Parse + structurally validate the manifest. Typed errors name
    the offending path; corrupt JSON is a CheckpointError, not a
    JSONDecodeError from three layers down."""
    if not os.path.isdir(dirname):
        raise CheckpointError(
            f"checkpoint directory '{dirname}' does not exist")
    path = os.path.join(dirname, MANIFEST_NAME)
    if not os.path.exists(path):
        raise CheckpointError(
            f"no manifest at '{path}' — is '{dirname}' a checkpoint "
            "directory? (save_checkpoint_tree / save_decoder_checkpoint "
            "write one)")
    try:
        with open(path, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        raise CheckpointError(f"manifest '{path}' unreadable: {e}") from e
    if not isinstance(manifest, dict) or "tensors" not in manifest \
            or ("payload" not in manifest and "payloads" not in manifest):
        raise CheckpointError(f"manifest '{path}' is not a checkpoint "
                              "manifest (missing payload/tensors)")
    fmt = manifest.get("format")
    if fmt != FORMAT_VERSION:
        raise CheckpointError(
            f"manifest '{path}' has format version {fmt!r}; this "
            f"reader understands {FORMAT_VERSION}")
    return manifest


def load_checkpoint_arrays(dirname: str, verify: bool = True,
                           _depth: int = 0
                           ) -> Tuple[Dict[str, np.ndarray],
                                      Dict[str, Any]]:
    """Load the flat ``{name: array}`` map. Arrays are NON-WRITEABLE
    zero-copy views over the mmap'd payload (the map stays alive
    exactly as long as the arrays). ``verify=True`` folds each
    segment's crc32 in bounded chunks first; a mismatch or a truncated
    payload raises ``CheckpointCorruptError`` naming the tensor.
    Delta checkpoints (entries marked ``"base": true``) resolve
    through the recorded base chain; a base tensor whose bytes no
    longer match the delta's recorded crc32 is named corruption, not a
    silent weight swap."""
    if _depth > 64:
        raise CheckpointError(
            f"checkpoint base chain at '{dirname}' exceeds 64 links — "
            "circular base references?")
    manifest = read_manifest(dirname)
    if "payloads" in manifest:
        # sharded layout (ISSUE 15): one payload per mesh shard, merged
        # manifest — delegate so every flat-view consumer (decoder
        # deploys, inspect/verify) reads both layouts transparently
        # (handing over the manifest we already parsed)
        from .sharded import load_sharded_arrays

        return load_sharded_arrays(dirname, verify=verify,
                                   _manifest=manifest)
    payload_path = os.path.join(dirname, manifest["payload"])
    if not os.path.exists(payload_path):
        # a CONCURRENT cross-process save may have committed between
        # our manifest read and here — its GC unlinks the payload our
        # (now stale) manifest references. Re-read once: a fresh
        # manifest naming a DIFFERENT payload means the directory is
        # healthy and simply moved on; the same payload still missing
        # means it really was deleted out from under the manifest.
        fresh = read_manifest(dirname)
        if "payloads" in fresh:
            # the overwriting save switched the directory to the
            # SHARDED layout — delegate, same recovery contract
            from .sharded import load_sharded_arrays

            return load_sharded_arrays(dirname, verify=verify,
                                       _manifest=fresh)
        if fresh["payload"] != manifest["payload"]:
            manifest = fresh
            payload_path = os.path.join(dirname, manifest["payload"])
    if not os.path.exists(payload_path):
        raise CheckpointError(
            f"manifest references missing payload '{payload_path}' — "
            "the checkpoint directory was partially deleted")
    with _tracing.span("checkpoint.load", dir=dirname,
                       tensors=len(manifest["tensors"])):
        mm, size = open_payload_map(payload_path)
        out: Dict[str, np.ndarray] = {}
        read = 0
        base_refs: List[Dict[str, Any]] = []
        for t in manifest["tensors"]:
            name = str(t["name"])
            if t.get("base"):
                base_refs.append(t)
                continue
            nbytes = int(t["nbytes"])
            # read-only view over the map: zero-copy
            out[name] = verified_segment(
                mm, size, payload_path, name, int(t["offset"]), nbytes,
                str(t["dtype"]), t["shape"], int(t["crc32"]), verify)
            read += nbytes
        if base_refs:
            base_rec = manifest.get("base")
            if not base_rec:
                raise CheckpointError(
                    f"manifest at '{dirname}' marks "
                    f"{len(base_refs)} tensor(s) as base-resident but "
                    "records no base checkpoint")
            base_dir = base_rec if os.path.isabs(base_rec) else \
                os.path.normpath(os.path.join(dirname, base_rec))
            base_arrays, base_manifest = load_checkpoint_arrays(
                base_dir, verify=verify, _depth=_depth + 1)
            base_idx = {str(bt["name"]): bt
                        for bt in base_manifest["tensors"]}
            for t in base_refs:
                name = str(t["name"])
                arr = base_arrays.get(name)
                bt = base_idx.get(name)
                if arr is None or bt is None:
                    _m_corrupt.inc()
                    raise CheckpointCorruptError(
                        f"tensor '{name}' is recorded as unchanged "
                        f"since base '{base_dir}', which no longer "
                        "holds it", tensor=name)
                # the delta pinned the exact crc/dtype/shape it
                # skipped: compare against the BASE MANIFEST's entry —
                # the recursive load above already byte-verified the
                # base's tensors against that manifest when
                # verify=True, so an O(1) metadata comparison catches
                # a drifted/replaced base without re-hashing (and
                # without copying) the mmap'd bytes a second time
                same = (str(bt["dtype"]) == str(t["dtype"])
                        and list(bt["shape"]) == list(t["shape"])
                        and int(bt["crc32"]) == int(t["crc32"]))
                if not same:
                    _m_corrupt.inc()
                    raise CheckpointCorruptError(
                        f"tensor '{name}' in base '{base_dir}' no "
                        f"longer matches the delta's recorded "
                        f"dtype/shape/crc — the base checkpoint "
                        "drifted under its delta", tensor=name)
                out[name] = arr
    _m_loads.inc()
    _m_bytes_read.inc(read)
    return out, manifest


def load_checkpoint_tree(dirname: str, verify: bool = True
                         ) -> Tuple[Any, Dict[str, Any]]:
    """Load and restore the nested tree structure (dicts/tuples/lists
    as saved). Returns ``(tree, manifest)``."""
    arrays, manifest = load_checkpoint_arrays(dirname, verify=verify)
    return restore_tree(arrays, manifest), manifest
