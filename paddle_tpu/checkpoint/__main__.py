"""CLI driver for the checkpoint subsystem.

    python -m paddle_tpu.checkpoint inspect DIR
        Print the manifest summary: format version, payload, meta,
        and per-tensor dtype/shape/offset/bytes.

    python -m paddle_tpu.checkpoint verify DIR
        Full checksum pass over every segment. Exit-nonzero with the
        OFFENDING TENSOR named on any corruption/truncation — the
        operator probe for "is this artifact deployable".

    python -m paddle_tpu.checkpoint --selftest
        In-process proof (no devices needed beyond jax-cpu): bitwise
        roundtrip, tuple-structure restore, named corruption/truncation
        failures, the torn-write crash discipline, decoder-contract
        validation, and decoder save/load logits equality. Wired into
        tools/check.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def _force_cpu():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def cmd_inspect(dirname: str) -> int:
    from .format import read_manifest

    m = read_manifest(dirname)
    sharded = "payloads" in m

    def _t_bytes(t):
        if sharded:
            return sum(int(s["nbytes"]) for s in t["segments"])
        return int(t["nbytes"])

    total = sum(_t_bytes(t) for t in m["tensors"])
    print(f"checkpoint {dirname}")
    print(f"  format:  v{m['format']}")
    if sharded:
        print(f"  payloads: {len(m['payloads'])} shard files over axis "
              f"'{m['shard_axis']}' ({total} tensor bytes, "
              f"{len(m['tensors'])} tensors)")
    else:
        print(f"  payload: {m['payload']} ({total} tensor bytes, "
              f"{len(m['tensors'])} tensors)")
    meta = m.get("meta") or {}
    if meta:
        print(f"  meta:    {json.dumps(meta, sort_keys=True)}")
    if m.get("base"):
        print(f"  base:    {m['base']}")
    for t in m["tensors"]:
        if sharded:
            dim = t.get("dim")
            loc = ("replicated" if dim is None
                   else f"dim {dim} over {len(t['segments'])} shards")
        else:
            # delta checkpoints: a base-resident tensor has no offset
            loc = "base" if t.get("base") else f"@{t['offset']}"
        print(f"  {t['name']:<24} {t['dtype']:<10} "
              f"{str(tuple(t['shape'])):<18} {loc} "
              f"({_t_bytes(t)} B)")
    return 0


def cmd_verify(dirname: str) -> int:
    from .format import CheckpointCorruptError, CheckpointError, \
        load_checkpoint_arrays

    try:
        arrays, m = load_checkpoint_arrays(dirname, verify=True)
    except CheckpointCorruptError as e:
        print(f"CORRUPT (tensor '{e.tensor}'): {e}")
        return 1
    except CheckpointError as e:
        print(f"INVALID: {e}")
        return 1
    total = sum(a.nbytes for a in arrays.values())
    what = (f"{len(m['payloads'])} shard payloads"
            if "payloads" in m else m["payload"])
    print(f"OK: {len(arrays)} tensors, {total} bytes, every "
          f"checksum verified ({what})")
    return 0


def run_selftest(verbose: bool = True) -> int:
    import numpy as np

    from paddle_tpu.distributed import faults
    from . import (CheckpointCorruptError, CheckpointError,
                   load_checkpoint_arrays, load_checkpoint_tree,
                   load_decoder_checkpoint, read_manifest,
                   save_checkpoint_tree, save_decoder_checkpoint)

    failures = []

    def check(ok, what):
        if verbose:
            print(("  ok  " if ok else "  FAIL") + f" {what}")
        if not ok:
            failures.append(what)

    with tempfile.TemporaryDirectory() as tmp:
        # -- 1. bitwise roundtrip + structure restore --------------------
        rng = np.random.RandomState(0)
        tree = {
            "emb": rng.randn(7, 6).astype(np.float32),
            "ln": (np.ones(6, np.float32), np.zeros(6, np.float32)),
            "ids": np.arange(5, dtype=np.int32),
        }
        d1 = os.path.join(tmp, "ck1")
        save_checkpoint_tree(d1, tree, meta={"step": 3})
        got, manifest = load_checkpoint_tree(d1)
        check(isinstance(got["ln"], tuple), "tuple structure restored")
        check(all(np.array_equal(a, b) for a, b in (
            (got["emb"], tree["emb"]), (got["ln"][0], tree["ln"][0]),
            (got["ids"], tree["ids"]))), "roundtrip is bitwise")
        flat, _ = load_checkpoint_arrays(d1)
        check(not flat["emb"].flags.writeable,
              "loaded arrays are zero-copy read-only views")
        check(manifest["meta"]["step"] == 3, "meta rides the manifest")

        # -- 2. corruption is typed and NAMED ----------------------------
        payload = os.path.join(d1, manifest["payload"])
        ent = next(t for t in manifest["tensors"] if t["name"] == "ids")
        with open(payload, "r+b") as f:
            f.seek(ent["offset"])
            b = f.read(1)
            f.seek(ent["offset"])
            f.write(bytes([b[0] ^ 0xFF]))
        try:
            load_checkpoint_arrays(d1)
            check(False, "bit flip detected")
        except CheckpointCorruptError as e:
            check(e.tensor == "ids" and "ids" in str(e),
                  "bit flip fails naming tensor 'ids'")
        with open(payload, "r+b") as f:  # heal for the next case
            f.seek(ent["offset"])
            f.write(b)
        with open(payload, "r+b") as f:
            f.truncate(ent["offset"] + 2)
        try:
            load_checkpoint_arrays(d1)
            check(False, "truncation detected")
        except CheckpointCorruptError as e:
            check(e.tensor == "ids", "truncation fails naming tensor")

        # -- 3. torn-write discipline: crash keeps the previous ----------
        d2 = os.path.join(tmp, "ck2")
        save_checkpoint_tree(d2, {"w": np.full(4, 1.0, np.float32)})
        with faults.scoped("crash@checkpoint.save:0"):
            try:
                save_checkpoint_tree(
                    d2, {"w": np.full(4, 2.0, np.float32)})
                check(False, "fault site fired")
            except faults.InjectedFault:
                check(True, "crash injected at checkpoint.save")
        got2, _ = load_checkpoint_tree(d2)
        check(float(got2["w"][0]) == 1.0,
              "crashed save left the previous checkpoint intact")
        save_checkpoint_tree(d2, {"w": np.full(4, 2.0, np.float32)})
        got2, m2 = load_checkpoint_tree(d2)
        orphans = [n for n in os.listdir(d2)
                   if n.startswith("segments-") and n != m2["payload"]]
        check(float(got2["w"][0]) == 2.0 and not orphans,
              "retry committed and swept the orphaned payload")

        # -- 4. decoder contract: save/load + validation -----------------
        from paddle_tpu.serving.decode import (DecoderSpec,
                                               build_decoder_params,
                                               decoder_step)

        spec = DecoderSpec(vocab=16, d_model=8, n_layers=1, n_heads=2,
                           n_kv_heads=1, seed=5)
        d3 = os.path.join(tmp, "dec")
        save_decoder_checkpoint(d3, spec, step=7)
        spec2, params2 = load_decoder_checkpoint(d3)
        check(spec2.to_dict() == spec.to_dict(),
              "DecoderSpec roundtrips through the manifest meta")
        import jax.numpy as jnp

        params = build_decoder_params(spec)
        pool = jnp.zeros((1, 3, 4, 1, 4), jnp.float32)
        args = (np.array([3], np.int32), np.array([0], np.int32),
                pool, pool,
                np.array([[1, 0, 0]], np.int32), np.array([1], np.int32))
        _, _, ref = decoder_step(params, spec, *args)
        _, _, got3 = decoder_step(params2, spec2, *args)
        check(np.array_equal(np.asarray(ref), np.asarray(got3)),
              "loaded decoder's logits are bitwise the saved one's")

        # re-save d1 first: case 2 left its payload truncated, and a
        # corrupt checkpoint would fail verification BEFORE the kind
        # check this case exists to prove
        save_checkpoint_tree(d1, tree, meta={"step": 3})
        try:
            load_decoder_checkpoint(d1)
            check(False, "non-decoder checkpoint refused")
        except CheckpointCorruptError:
            check(False, "non-decoder refusal reached the kind check")
        except CheckpointError:
            check(True, "non-decoder checkpoint refused (typed)")
        # a tensor the spec doesn't expect fails NAMED, pre-device
        save_checkpoint_tree(
            d3, {**build_decoder_params(spec), "rogue": np.zeros(2)},
            meta=read_manifest(d3)["meta"])
        try:
            load_decoder_checkpoint(d3)
            check(False, "contract drift refused")
        except CheckpointError as e:
            check("rogue" in str(e),
                  "contract drift names the unexpected tensor")

    if failures:
        print(f"checkpoint selftest: {len(failures)} FAILURE(S): "
              f"{failures}")
        return 1
    print("checkpoint selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m paddle_tpu.checkpoint")
    ap.add_argument("--selftest", action="store_true",
                    help="run the in-process selftest")
    sub = ap.add_subparsers(dest="cmd")
    p_ins = sub.add_parser("inspect", help="print a manifest summary")
    p_ins.add_argument("dir")
    p_ver = sub.add_parser("verify", help="full checksum pass")
    p_ver.add_argument("dir")
    args = ap.parse_args(argv)

    _force_cpu()
    if args.selftest:
        return run_selftest()
    if args.cmd == "inspect":
        return cmd_inspect(args.dir)
    if args.cmd == "verify":
        return cmd_verify(args.dir)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
