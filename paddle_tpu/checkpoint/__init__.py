"""paddle_tpu.checkpoint — versioned, verifiable model checkpoints
(ISSUE 12).

A manifest JSON indexes per-tensor raw binary segments (dtype / shape /
byte offset / crc32) in a nonce-named payload file; commits are atomic
(tmp + fsync + rename, the ``master.snapshot`` torn-write discipline —
the ``checkpoint.save`` fault site sits at the commit point for chaos
plans), loads are chunk-verified zero-copy mmap views, and corruption
fails with the tensor NAMED. ``save_decoder_checkpoint`` /
``load_decoder_checkpoint`` target the serving ``DecoderSpec`` /
``decoder_step`` contract so ``load_decoder(checkpoint_dir=...)`` can
deploy real weights — locally, over RPC, or fleet-wide through the
controller's intent log. See docs/CHECKPOINT.md.

    python -m paddle_tpu.checkpoint inspect DIR   # manifest summary
    python -m paddle_tpu.checkpoint verify DIR    # full checksum pass
    python -m paddle_tpu.checkpoint --selftest    # in-process proof
"""
from .decoder import (decoder_checkpoint_mesh, expected_decoder_tensors,
                      load_decoder_checkpoint, save_decoder_checkpoint)
from .format import (CheckpointCorruptError, CheckpointError,
                     CheckpointWriter, load_checkpoint_arrays,
                     load_checkpoint_tree, read_manifest,
                     save_checkpoint_tree)
from .sharded import (load_sharded_arrays, load_sharded_checkpoint,
                      save_sharded_checkpoint)

__all__ = [
    "CheckpointError", "CheckpointCorruptError", "CheckpointWriter",
    "save_checkpoint_tree", "load_checkpoint_tree",
    "load_checkpoint_arrays", "read_manifest",
    "save_decoder_checkpoint", "load_decoder_checkpoint",
    "expected_decoder_tensors", "decoder_checkpoint_mesh",
    "save_sharded_checkpoint", "load_sharded_checkpoint",
    "load_sharded_arrays",
]
