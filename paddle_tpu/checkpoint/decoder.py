"""Decoder checkpoints: persist/restore the ``DecoderSpec`` /
``decoder_step`` parameter-tree contract (ISSUE 12).

``save_decoder_checkpoint`` writes the spec into the manifest's meta
and the parameter tree into the payload; ``load_decoder_checkpoint``
restores both and VALIDATES the tensor set against the spec before
anything touches a device — a missing, extra, or wrong-shape tensor is
a typed error naming the tensor, never a shape error three layers into
``decoder_step``. Round-trips are bitwise: a loaded decoder serves
exactly the tokens the saving engine served (tier-1 pins greedy
equality through a fresh server)."""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from .format import (CheckpointError, load_checkpoint_tree,
                     save_checkpoint_tree)

__all__ = ["save_decoder_checkpoint", "load_decoder_checkpoint",
           "expected_decoder_tensors", "decoder_checkpoint_mesh"]


def expected_decoder_tensors(spec) -> Dict[str, Tuple[int, ...]]:
    """Flat ``{name: shape}`` the decoder param-tree contract implies
    for ``spec`` — computed analytically (no parameter draws), so
    validation is cheap even for models whose seed-build would not be.
    The names mirror ``build_decoder_params``'s tree under the
    ``format._flatten`` scheme (tuples index as ``/0``, ``/1``)."""
    dm, dh = spec.d_model, spec.head_dim
    out: Dict[str, Tuple[int, ...]] = {
        "tok_emb": (spec.vocab, dm),
        "lnf/0": (dm,),
        "lnf/1": (dm,),
    }
    for l in range(spec.n_layers):
        p = f"layer{l}"
        out[f"{p}/ln1/0"] = (dm,)
        out[f"{p}/ln1/1"] = (dm,)
        out[f"{p}/wq"] = (dm, spec.n_heads * dh)
        out[f"{p}/wk"] = (dm, spec.n_kv_heads * dh)
        out[f"{p}/wv"] = (dm, spec.n_kv_heads * dh)
        out[f"{p}/wo"] = (spec.n_heads * dh, dm)
        out[f"{p}/ln2/0"] = (dm,)
        out[f"{p}/ln2/1"] = (dm,)
        out[f"{p}/w1"] = (dm, 4 * dm)
        out[f"{p}/w2"] = (4 * dm, dm)
    return out


def save_decoder_checkpoint(dirname: str, spec,
                            params: Optional[Dict[str, Any]] = None,
                            step: Optional[int] = None,
                            base_manifest: Optional[str] = None,
                            mesh_axes: Optional[Any] = None,
                            mesh_rules: Optional[Any] = None,
                            shard_axis: Optional[str] = None) -> str:
    """Persist a decoder (spec + parameter tree) as a manifest
    checkpoint. ``params=None`` saves the spec's deterministic
    seed-built tree (the test/bench vehicle); a live engine passes its
    own tree. ``step`` (optional) rides the meta so
    ``fluid.io.latest_checkpoint_step`` recognizes the directory.
    ``base_manifest`` (ISSUE 13, the rollout loop's incremental save)
    names a prior decoder checkpoint DIRECTORY: only tensors whose
    crc32 differs from the base are written — the rest become base
    references the loader follows — so a fine-tune that touched two
    layers costs two layers of payload, not the whole model.

    ``mesh_axes`` (ISSUE 15) RECORDS the serving mesh in the manifest
    meta — ``load_decoder(checkpoint_dir=)`` then deploys the engine
    sharded exactly as exported, no operator knob needed;
    ``mesh_rules`` overrides the default ``mesh.decoder_rules``.
    ``shard_axis`` additionally writes the SHARDED payload layout (one
    file per shard of that mesh axis, merged manifest) instead of one
    monolithic payload; it requires ``mesh_axes`` and is incompatible
    with ``base_manifest`` (delta chains are a monolithic-layout
    feature)."""
    import numpy as _np

    from ..serving.decode import build_decoder_params

    if params is None:
        params = build_decoder_params(spec)
    meta: Dict[str, Any] = {"kind": "decoder", "spec": spec.to_dict()}
    if step is not None:
        meta["step"] = int(step)
    if shard_axis is not None and mesh_axes is None:
        raise CheckpointError(
            "shard_axis needs mesh_axes — the shard count is that mesh "
            "axis's size")
    if mesh_axes is not None:
        from ..mesh import MeshSpec, ShardingRules, decoder_rules

        ms = MeshSpec.coerce(mesh_axes)
        rules = ShardingRules.coerce(mesh_rules, default=decoder_rules)
        if shard_axis is not None:
            if base_manifest is not None:
                raise CheckpointError(
                    "sharded decoder checkpoints do not support "
                    "base_manifest deltas — save monolithic or full")
            import jax

            from .sharded import save_sharded_checkpoint

            # jax arrays (possibly device-sharded) -> host before the
            # splitter slices them
            host = jax.tree_util.tree_map(_np.asarray, params)
            return save_sharded_checkpoint(
                dirname, host, shard_axis=str(shard_axis),
                mesh_spec=ms, rules=rules, meta=meta)
        meta["mesh"] = {"spec": ms.to_dict(), "rules": rules.to_dict()}
    return save_checkpoint_tree(dirname, params, meta=meta,
                                base=base_manifest)


def decoder_checkpoint_mesh(dirname: str) -> Optional[Dict[str, Any]]:
    """The mesh a decoder checkpoint RECORDED at export (``{"spec":
    MeshSpec dict, "rules": ShardingRules dict}``), or None for
    single-chip artifacts. Reads only the manifest — no payload I/O —
    so the serving deploy path can decide the engine's mesh before
    loading a single tensor."""
    from .format import read_manifest

    manifest = read_manifest(dirname)
    meta = manifest.get("meta") or {}
    return meta.get("mesh")


def load_decoder_checkpoint(dirname: str, verify: bool = True):
    """Restore ``(DecoderSpec, params)`` from a decoder checkpoint.
    The params come back as jax arrays ready for ``DecodeEngine(...,
    params=)``; the tensor set is validated against the spec FIRST
    (names and shapes), so a wrong-model or hand-edited checkpoint
    fails with the offending tensor named."""
    import jax.numpy as jnp

    from ..serving.decode import DecoderSpec

    tree, manifest = load_checkpoint_tree(dirname, verify=verify)
    meta = manifest.get("meta") or {}
    if meta.get("kind") != "decoder":
        raise CheckpointError(
            f"'{dirname}' is a {meta.get('kind') or 'generic'} "
            "checkpoint, not a decoder checkpoint (no DecoderSpec in "
            "its meta)")
    spec = DecoderSpec.from_dict(dict(meta["spec"]))

    # validate the FLAT view against the analytic contract before any
    # device transfer
    from .format import _flatten

    flat, _skel = _flatten(tree)
    want = expected_decoder_tensors(spec)
    missing = sorted(set(want) - set(flat))
    extra = sorted(set(flat) - set(want))
    if missing or extra:
        raise CheckpointError(
            f"decoder checkpoint '{dirname}' does not match its spec's "
            f"parameter contract: missing {missing or 'none'}, "
            f"unexpected {extra or 'none'}")
    for name, shape in want.items():
        got = tuple(flat[name].shape)
        if got != shape:
            raise CheckpointError(
                f"tensor '{name}' in '{dirname}' has shape {got}, "
                f"spec requires {shape}")
        dt = np.dtype(flat[name].dtype)
        if dt != np.float32:
            # refuse, don't downcast: jnp.asarray would silently
            # squeeze a float64 (or quantized) tree into float32 and
            # the served tokens would differ from the saved model's —
            # the bitwise-roundtrip promise dies without a named error
            raise CheckpointError(
                f"tensor '{name}' in '{dirname}' is {dt}, the decoder "
                f"contract serves float32 — convert at save time, "
                "never implicitly at deploy")

    def to_device(node):
        if isinstance(node, dict):
            return {k: to_device(v) for k, v in node.items()}
        if isinstance(node, tuple):
            return tuple(to_device(v) for v in node)
        if isinstance(node, list):
            return [to_device(v) for v in node]
        return jnp.asarray(np.asarray(node))

    return spec, to_device(tree)
